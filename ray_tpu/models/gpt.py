"""GPT-style decoder LM, TPU-first (the flagship model family).

Pure-JAX pytree params (no framework wrapper) whose path names line up with
ray_tpu.parallel.sharding rules: `layers/<i>/attn/wq`, `mlp/w_up`,
`embed/table`, `lm_head`, `moe/...`. Design choices for the MXU/HBM:
bfloat16 activations + params with fp32 softmax/layernorm accumulation,
flash-attention Pallas kernel, optional ring attention (sequence sharded),
optional MoE (expert-parallel), per-layer jax.checkpoint (remat) for memory.

Capability parity target: the models RLlib/Train wrap in the reference are
torch modules; here the model is a (init, apply) pair compatible with pjit.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.attention import flash_attention, mha_reference, ring_attention


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304           # GPT-2 vocab padded to a multiple of 128
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    rope_theta: float = 10000.0
    rmsnorm_eps: float = 1e-5
    # MoE: 0 = dense MLPs; >0 = that many experts with top-2 routing.
    n_experts: int = 0
    expert_top_k: int = 2
    remat: bool = True
    # Remat granularity: None -> "full" if remat else "none".
    #   "full": recompute the whole layer in backward (min HBM, max FLOPs)
    #   "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable —
    #           weight-matmul outputs saved, elementwise recomputed
    #   "none": save everything (max HBM, min FLOPs)
    remat_policy: Optional[str] = None
    attention: str = "flash"          # flash | reference | ring
    # Flash kernel tile sizes (perf knob; correctness-invariant).
    flash_block_q: int = 128
    flash_block_k: int = 128
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def gpt2_small() -> "GPTConfig":
        return GPTConfig()

    @staticmethod
    def gpt2_medium() -> "GPTConfig":
        return GPTConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                         d_ff=256, max_seq=128)


def _init_dense(key, shape, scale=None, dtype=jnp.float32):
    if scale is None:
        scale = 1.0 / math.sqrt(shape[0])
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def gpt_init(key, cfg: GPTConfig) -> Dict:
    """Build the parameter pytree (fp32 master weights)."""
    keys = jax.random.split(key, cfg.n_layers + 3)
    params: Dict[str, Any] = {
        "embed": {"table": _init_dense(keys[0], (cfg.vocab_size, cfg.d_model),
                                       scale=0.02)},
        "final_norm": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _init_dense(keys[1], (cfg.d_model, cfg.vocab_size))
    layers = []
    d, ff, e = cfg.d_model, cfg.d_ff, cfg.n_experts
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[i + 2], 8)
        layer = {
            "ln1": {"scale": jnp.ones((d,), jnp.float32)},
            "ln2": {"scale": jnp.ones((d,), jnp.float32)},
            "attn": {
                "wq": _init_dense(k[0], (d, d)),
                "wk": _init_dense(k[1], (d, d)),
                "wv": _init_dense(k[2], (d, d)),
                "wo": _init_dense(k[3], (d, d),
                                  scale=1.0 / math.sqrt(2 * cfg.n_layers * d)),
            },
        }
        if e > 0:
            layer["moe"] = {
                "router": _init_dense(k[4], (d, e), scale=0.02),
                "w_gate": _init_dense(k[5], (e, d, ff)),
                "w_up": _init_dense(k[6], (e, d, ff)),
                "w_down": _init_dense(k[7], (e, ff, d),
                                      scale=1.0 / math.sqrt(2 * cfg.n_layers * ff)),
            }
        else:
            layer["mlp"] = {
                "w_gate": _init_dense(k[5], (d, ff)),
                "w_up": _init_dense(k[6], (d, ff)),
                "w_down": _init_dense(k[7], (ff, d),
                                      scale=1.0 / math.sqrt(2 * cfg.n_layers * ff)),
            }
        layers.append(layer)
    params["layers"] = layers
    return params


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _rope(x, theta: float, positions):
    """Rotary position embeddings; x: [B, H, S, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, None, :, :]
    sin = jnp.sin(angles)[:, None, :, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _attention_block(layer, x, cfg: GPTConfig, positions, mesh):
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    dt = cfg.dtype

    def proj(w):
        return jnp.einsum("bsd,de->bse", x, w.astype(dt))

    q = proj(layer["attn"]["wq"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    k = proj(layer["attn"]["wk"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    v = proj(layer["attn"]["wv"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    q = _rope(q, cfg.rope_theta, positions)
    k = _rope(k, cfg.rope_theta, positions)
    if cfg.attention == "ring":
        o = ring_attention(q, k, v, mesh=mesh, causal=True)
    elif cfg.attention == "reference":
        o = mha_reference(q, k, v, causal=True)
    else:
        o = flash_attention(q, k, v, causal=True,
                            block_q=cfg.flash_block_q,
                            block_k=cfg.flash_block_k)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
    return jnp.einsum("bsd,de->bse", o, layer["attn"]["wo"].astype(dt))


def _mlp_block(layer, x, cfg: GPTConfig):
    dt = cfg.dtype
    m = layer["mlp"]
    gate = jnp.einsum("bsd,df->bsf", x, m["w_gate"].astype(dt))
    up = jnp.einsum("bsd,df->bsf", x, m["w_up"].astype(dt))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up,
                      m["w_down"].astype(dt))


def _moe_block(layer, x, cfg: GPTConfig):
    """Top-k routed MoE with dense dispatch (einsum over one-hot combine
    weights) — compiles to static shapes; the 'expert' mesh axis shards the
    expert dimension of w_gate/w_up/w_down (expert parallelism, net-new vs
    the reference per SURVEY.md §2.5)."""
    dt = cfg.dtype
    m = layer["moe"]
    e = cfg.n_experts
    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32),
                        m["router"].astype(jnp.float32))
    weights, idx = jax.lax.top_k(jax.nn.softmax(logits, axis=-1),
                                 cfg.expert_top_k)
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)       # [b,s,k,e]
    combine = jnp.einsum("bsk,bske->bse", weights, onehot)   # [b,s,e]
    gate = jnp.einsum("bsd,edf->bsef", x, m["w_gate"].astype(dt))
    up = jnp.einsum("bsd,edf->bsef", x, m["w_up"].astype(dt))
    act = jax.nn.silu(gate) * up
    out = jnp.einsum("bsef,efd->bsed", act, m["w_down"].astype(dt))
    y = jnp.einsum("bsed,bse->bsd", out.astype(jnp.float32), combine)
    # Load-balancing auxiliary loss (Switch-style).
    density = jnp.mean(onehot[:, :, 0, :], axis=(0, 1))
    router_prob = jnp.mean(jax.nn.softmax(logits, -1), axis=(0, 1))
    aux = e * jnp.sum(density * router_prob)
    return y.astype(dt), aux


def gpt_forward(params, tokens, cfg: GPTConfig, mesh=None, act_sharding=None):
    """tokens: [B, S] int32 -> logits [B, S, vocab] (cfg.dtype)."""
    dt = cfg.dtype
    x, aux_total = gpt_backbone(params, tokens, cfg, mesh, act_sharding)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed"]["table"].astype(dt))
    else:
        logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(dt))
    return logits, aux_total


def gpt_backbone(params, tokens, cfg: GPTConfig, mesh=None, act_sharding=None):
    """tokens: [B, S] -> final hidden states [B, S, D] (pre-LM-head).

    act_sharding (a NamedSharding for [B, S, D] activations, usually
    ``strategy.activation_sharding(mesh)``) pins the residual stream at
    layer boundaries so GSPMD never back-propagates weight shardings onto
    activation gradients (the "involuntary full rematerialization" failure
    mode on 2D tp_fsdp meshes).
    """
    b, s = tokens.shape
    dt = cfg.dtype

    def _c(x):
        if act_sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, act_sharding)

    x = _c(params["embed"]["table"].astype(dt)[tokens])
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    aux_total = 0.0

    def layer_fn(x, layer):
        h = _c(x + _attention_block(layer, _rmsnorm(
            x, layer["ln1"]["scale"], cfg.rmsnorm_eps), cfg, positions, mesh))
        normed = _rmsnorm(h, layer["ln2"]["scale"], cfg.rmsnorm_eps)
        if cfg.n_experts > 0:
            delta, aux = _moe_block(layer, normed, cfg)
        else:
            delta, aux = _mlp_block(layer, normed, cfg), 0.0
        return _c(h + delta), aux

    policy = cfg.remat_policy or ("full" if cfg.remat else "none")
    if policy == "full":
        layer_fn = jax.checkpoint(layer_fn)
    elif policy == "dots":
        layer_fn = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif policy != "none":
        raise ValueError(f"unknown remat_policy {policy!r} "
                         "(expected 'full' | 'dots' | 'none')")
    for layer in params["layers"]:
        x, aux = layer_fn(x, layer)
        aux_total = aux_total + aux
    return _rmsnorm(x, params["final_norm"]["scale"], cfg.rmsnorm_eps), \
        aux_total


def chunked_xent(x, w_head, targets, mask, chunk_rows: int = 16384):
    """Next-token cross-entropy WITHOUT materializing full [N, vocab] fp32
    logits (12.8 GB at bs=64/seq=1024/vocab=50k — an HBM-capacity bug for
    any capacity-size batch). Rows are processed in chunks under
    jax.checkpoint, so the backward recomputes each chunk's logits instead
    of saving them. TPU-native analogue of fused linear+cross-entropy.

    x: [N, D] (model dtype), w_head: [D, V], targets: [N] int32,
    mask: [N] fp32. Returns (sum_nll, sum_mask).
    """
    n, d = x.shape
    # Never chunk coarser than the batch itself: padding a small batch up
    # to a full 16k-row chunk would both waste LM-head FLOPs and raise the
    # HBM peak the chunking exists to cut.
    chunk_rows = min(chunk_rows, max(128, n))
    pad = (-n) % chunk_rows
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        mask = jnp.pad(mask, (0, pad))
    n_chunks = (n + pad) // chunk_rows
    xc = x.reshape(n_chunks, chunk_rows, d)
    tc = targets.reshape(n_chunks, chunk_rows)
    mc = mask.reshape(n_chunks, chunk_rows)

    @jax.checkpoint
    def body(carry, args):
        xk, tk, mk = args
        logits = (xk @ w_head).astype(jnp.float32)       # [chunk, V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, tk[:, None], axis=-1)[:, 0]
        nll = lse - picked
        return (carry[0] + jnp.sum(nll * mk), carry[1] + jnp.sum(mk)), None

    (total, denom), _ = jax.lax.scan(body, (0.0, 0.0), (xc, tc, mc))
    return total, denom


def gpt_loss(params, batch, cfg: GPTConfig, mesh=None, act_sharding=None):
    """batch: {"tokens": [B, S+1]} -> mean next-token cross-entropy.

    The LM-head matmul + softmax run chunked (chunked_xent) so the full
    fp32 logits tensor never exists in HBM.
    """
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    x, aux = gpt_backbone(params, inputs, cfg, mesh, act_sharding)
    b, s, d = x.shape
    dt = cfg.dtype
    if cfg.tie_embeddings:
        w_head = params["embed"]["table"].astype(dt).T
    else:
        w_head = params["lm_head"].astype(dt)
    mask = (targets >= 0).astype(jnp.float32)
    total, denom = chunked_xent(x.reshape(b * s, d), w_head,
                                targets.reshape(b * s),
                                mask.reshape(b * s))
    loss = total / jnp.maximum(denom, 1.0)
    if cfg.n_experts > 0:
        loss = loss + 0.01 * aux / cfg.n_layers
    return loss


def count_params(params) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
