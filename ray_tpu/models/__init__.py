from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_forward, gpt_loss

__all__ = ["GPTConfig", "gpt_init", "gpt_forward", "gpt_loss"]
