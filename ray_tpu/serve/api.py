"""Public serve API (reference: python/ray/serve/api.py — serve.start :62,
serve.run :523, serve.shutdown, status)."""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

_controller = None


def _get_controller():
    global _controller
    if _controller is not None:
        return _controller
    try:
        _controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        # Restartable detached named actor: a controller crash (or
        # preemption) restarts it uncharged, and the fresh instance
        # recovers its persisted target state and REATTACHES live
        # replicas (see serve/persistence.py) instead of cold-starting.
        cls = ray_tpu.remote(num_cpus=0.1, name=CONTROLLER_NAME,
                             get_if_exists=True, max_restarts=-1,
                             lifetime="detached")(ServeController)
        _controller = cls.remote()
    return _controller


async def _get_controller_async():
    """Controller lookup legal on the core loop (replicas/proxy)."""
    global _controller
    if _controller is None:
        from ray_tpu._private import worker_api
        from ray_tpu.actor import ActorHandle
        core = worker_api.get_core()
        info = await core.get_named_actor(CONTROLLER_NAME, "")
        _controller = ActorHandle._from_actor_info(info)
    return _controller


def start(*, http_options=None, proxy: bool = False,
          grpc_options=None, grpc_proxy: bool = False, config=None):
    """Start the Serve control plane (controller, optionally the HTTP
    proxy and/or the binary-RPC ingress — reference: gRPCProxy).
    ``config`` (a ServeConfig) sets cluster-level control-plane knobs;
    they persist to the serve KV so controller recovery keeps them."""
    ctrl = _get_controller()
    if config is not None:
        from dataclasses import asdict
        ray_tpu.get(ctrl.set_serve_config.remote(asdict(config)),
                    timeout=30)
    if proxy or http_options is not None:
        from ray_tpu.serve.config import HTTPOptions
        opts = http_options or HTTPOptions()
        ray_tpu.get(ctrl.ensure_proxy.remote(opts.host, opts.port),
                    timeout=30)
    if grpc_proxy or grpc_options is not None:
        from ray_tpu.serve.config import gRPCOptions
        gopts = grpc_options or gRPCOptions()
        ray_tpu.get(
            ctrl.ensure_grpc_proxy.remote(gopts.host, gopts.port),
            timeout=30)
    return ctrl


def get_grpc_address() -> str:
    """Address of the binary-RPC ingress (connect a ServeRpcClient)."""
    return ray_tpu.get(
        _get_controller().get_grpc_address.remote(), timeout=30)


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/",
        _blocking_until_ready: bool = True) -> DeploymentHandle:
    """Deploy an application graph; returns a handle to its ingress."""
    import cloudpickle
    if isinstance(app, Deployment):
        app = app.bind()
    ctrl = _get_controller()
    flat = app.flatten()
    payload = []
    for dep_name, a in flat.items():
        d = a.deployment
        payload.append({
            "name": dep_name,
            "version": d.version,
            "config": d.config,
            "blob": cloudpickle.dumps({
                "func_or_class": d.func_or_class,
                "init_args": a.init_args,
                "init_kwargs": a.init_kwargs,
                "app_name": name,
            }),
        })
    ingress = app.deployment.name
    ray_tpu.get(ctrl.deploy_app.remote(name, payload, route_prefix, ingress),
                timeout=120)
    handle = DeploymentHandle(ingress, app_name=name)
    if _blocking_until_ready:
        deadline = time.time() + 60
        while time.time() < deadline:
            _v, reps = ray_tpu.get(
                ctrl.get_replicas.remote(name, ingress), timeout=30)
            if reps:
                break
            time.sleep(0.1)
    return handle


def delete(name: str):
    ctrl = _get_controller()
    ray_tpu.get(ctrl.delete_app.remote(name), timeout=60)


def status() -> Dict[str, Any]:
    ctrl = _get_controller()
    return ray_tpu.get(ctrl.status.remote(), timeout=30)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ctrl = _get_controller()
    routes = ray_tpu.get(ctrl.get_route_table.remote(), timeout=30)
    for _route, (app, ingress) in routes.items():
        if app == name:
            return DeploymentHandle(ingress, app_name=name)
    st = ray_tpu.get(ctrl.status.remote(), timeout=30)
    if name in st and st[name]:
        return DeploymentHandle(next(iter(st[name])), app_name=name)
    raise ValueError(f"no app named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name=app_name)


def shutdown():
    global _controller
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        _controller = None
        return
    try:
        ray_tpu.get(ctrl.shutdown.remote(), timeout=60)
        ray_tpu.kill(ctrl)
    except Exception:
        pass
    _controller = None
