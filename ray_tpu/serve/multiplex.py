"""Model multiplexing: many models share a replica pool.

Reference parity: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) and
serve.get_multiplexed_model_id. The loader is LRU-bounded per replica; the
requested model id rides the request context set by the replica actor.

Routing awareness: every load/evict updates the owner's
``__serve_mux_resident__`` set, which ReplicaActor.get_metrics() exposes,
the controller polls alongside health checks, and the routing table
publishes — so handles route a model-id-tagged request to a replica that
already holds the model (no cold load, no LRU thrash) whenever one
exists, falling back to least-loaded otherwise.
"""

from __future__ import annotations

import asyncio
import functools
from collections import OrderedDict
from typing import Any, Callable

from ray_tpu.serve.replica import get_request_context

# Well-known attr on the deployment callable instance: the union of
# model ids currently cached by every @serve.multiplexed method on it.
RESIDENT_ATTR = "__serve_mux_resident__"


def get_multiplexed_model_id() -> str:
    ctx = get_request_context()
    return ctx.multiplexed_model_id if ctx else ""


def _publish_resident(owner, cache: "OrderedDict") -> None:
    """Refresh the owner's resident-model set after a load or evict.
    One flat set per owner (multiple decorated methods union into it via
    per-method caches — evicting from one method's cache recomputes from
    all of them)."""
    try:
        caches = getattr(owner, "__serve_mux_caches__", None)
        if caches is None:
            caches = []
            setattr(owner, "__serve_mux_caches__", caches)
        if not any(c is cache for c in caches):   # identity, not dict ==
            caches.append(cache)
        resident = set()
        for c in caches:
            resident.update(c.keys())
        setattr(owner, RESIDENT_ATTR, resident)
    except Exception:  # noqa: BLE001 — routing hint only, never fails a load
        pass


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator over `async def load_model(self, model_id)`; calling the
    wrapper with a model id returns a cached model, evicting LRU."""

    def wrap(fn):
        attr = f"__serve_mux_cache_{fn.__name__}"
        loading_attr = f"__serve_mux_loading_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(owner, model_id: str):
            cache: OrderedDict = getattr(owner, attr, None)
            if cache is None:
                cache = OrderedDict()
                setattr(owner, attr, cache)
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # Single-flight per model id: concurrent requests for the same
            # uncached model share one load (a duplicate load would be
            # dropped without its unload hook — a device-memory leak).
            loading: dict = getattr(owner, loading_attr, None)
            if loading is None:
                loading = {}
                setattr(owner, loading_attr, loading)
            if model_id in loading:
                return await asyncio.shield(loading[model_id])

            async def load():
                model = fn(owner, model_id)
                if asyncio.iscoroutine(model):
                    model = await model
                return model

            task = asyncio.ensure_future(load())
            loading[model_id] = task
            try:
                model = await task
            finally:
                loading.pop(model_id, None)
            cache[model_id] = model
            cache.move_to_end(model_id)
            _publish_resident(owner, cache)
            while len(cache) > max_num_models_per_replica:
                _old_id, old_model = cache.popitem(last=False)
                _publish_resident(owner, cache)
                # Give the model an explicit release hook (device memory is
                # not guaranteed to free on refcount drop alone).
                unload = getattr(old_model, "unload", None)
                if callable(unload):
                    try:
                        res = unload()
                        if asyncio.iscoroutine(res):
                            await res
                    except Exception:
                        pass
                del old_model
            return model

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
