"""SLO burn-rate engine: the controller-side consumer of the request
observability plane.

Inputs are the per-replica SLO counters the controller already polls for
autoscaling (`ReplicaActor.get_metrics`: cumulative completed / slow /
errors / shed / timeouts — counted for EVERY request, independent of
trace sampling; the counters are themselves fed by the same request
phase stamps that build `ray_tpu_serve_request_phase_seconds`). The
engine turns cumulative snapshots into per-poll deltas, accumulates them
into one-second buckets, and evaluates the classic multi-window
burn-rate condition:

    burn(w) = bad_fraction(w) / (1 - slo)

A deployment is VIOLATING when both the fast and the slow window burn
above `SLOConfig.burn_threshold` (fast alone = maybe a blip; slow alone
= an old episode still draining out of the window). Violations export as
`ray_tpu_serve_slo_burn_rate{Deployment,Window}` gauges plus a
`ray_tpu_serve_slo_violations_total` edge counter, and — when the
deployment autoscales — drive a scale-up BEFORE the bounded replica
queue ever sheds a request (serve/controller.py `_autoscale`).

Replica restarts are absorbed: a cumulative counter that goes BACKWARDS
(fresh replica, id reuse) clamps its delta to the new absolute value.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional, Tuple

# Cumulative replica counters the engine consumes. `completed` counts
# finished execs (success or app error); shed/timeouts never reach exec,
# so total = completed + shed + timeouts and the bad categories are
# disjoint by construction (replica.py _account_exec).
_TOTAL_KEYS = ("completed", "shed", "timeouts")
_BAD_KEYS = ("slow", "errors", "shed", "timeouts")
_KEYS = ("completed", "slow", "errors", "shed", "timeouts")


class _WindowRing:
    """One-second (total, bad) buckets over the longest window — O(1)
    add, O(window) sum (windows are <= minutes; the controller polls
    twice a second at most)."""

    def __init__(self, span_s: float):
        self._n = max(2, int(math.ceil(span_s)) + 1)
        self._total = [0.0] * self._n
        self._bad = [0.0] * self._n
        self._stamps = [0.0] * self._n   # bucket epoch-second or 0

    def add(self, now: float, total: float, bad: float) -> None:
        sec = int(now)
        i = sec % self._n
        if self._stamps[i] != sec:
            self._stamps[i] = sec
            self._total[i] = 0.0
            self._bad[i] = 0.0
        self._total[i] += total
        self._bad[i] += bad

    def sums(self, now: float, window_s: float) -> Tuple[float, float]:
        lo = int(now) - int(math.ceil(window_s)) + 1
        total = bad = 0.0
        for i in range(self._n):
            if self._stamps[i] >= lo and self._stamps[i] <= int(now):
                total += self._total[i]
                bad += self._bad[i]
        return total, bad


def _burn_gauge():
    from ray_tpu.util import metrics
    return metrics.Gauge(
        "ray_tpu_serve_slo_burn_rate",
        "error-budget burn rate per SLO window (bad_fraction / "
        "(1 - slo)); sustained burn above the deployment's threshold "
        "in BOTH windows is an SLO violation",
        tag_keys=("Deployment", "Window"))


def _violations_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_slo_violations_total",
        "SLO violation episodes (multi-window burn crossed the "
        "threshold): each count is one False->True edge",
        tag_keys=("Deployment",))


class DeploymentSLO:
    """Burn-rate state for one deployment."""

    def __init__(self, deployment: str, cfg):
        self.deployment = deployment
        self.cfg = cfg
        self._last: Dict[str, Dict[str, float]] = {}
        self._ring = _WindowRing(cfg.slow_window_s)
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.violating = False
        self.violations = 0
        # Burn-idle tracking (downscale gate): seeded NOW so a fresh
        # engine (deploy, controller restart) must observe a full quiet
        # slow window before it can vouch for a scale-down.
        self._last_burn_ts = time.time()

    # ------------------------------------------------------------------
    def ingest(self, replica_metrics: Dict[str, dict],
               now: Optional[float] = None) -> None:
        """Fold one controller poll: cumulative snapshots -> deltas ->
        window buckets. `replica_metrics` maps replica_id -> the dict
        ReplicaActor.get_metrics returned (replicas that failed the poll
        are simply absent — their counts arrive with the next poll)."""
        now = time.time() if now is None else now
        total_d = bad_d = 0.0
        for rid, m in replica_metrics.items():
            prev = self._last.get(rid)
            cur = {k: float(m.get(k, 0.0)) for k in _KEYS}
            if prev is None:
                # First sight of this replica (fresh engine after a
                # controller restart / redeploy, or a fresh replica):
                # its cumulative counters cover an UNKNOWN span of time,
                # so charging them into one second-bucket would let
                # hours-old history trip an instant dual-window
                # violation. Record the baseline; deltas start next poll.
                self._last[rid] = cur
                continue
            delta = {}
            for k in _KEYS:
                d = cur[k] - prev[k]
                # Restarted replica (counter reset): charge the new
                # absolute value, never a negative delta.
                delta[k] = cur[k] if d < 0 else d
            self._last[rid] = cur
            total_d += sum(delta[k] for k in _TOTAL_KEYS)
            bad_d += sum(delta[k] for k in _BAD_KEYS)
        # Forget replicas no longer reporting (retired/dead).
        gone = set(self._last) - set(replica_metrics)
        for rid in gone:
            del self._last[rid]
        self._ring.add(now, total_d, min(bad_d, total_d))

    # ------------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        """Recompute burn rates; returns {"fast","slow","violating",
        "new_violation","idle_s"} and exports the gauges/counter."""
        now = time.time() if now is None else now
        budget = max(1e-9, 1.0 - self.cfg.slo)

        def burn(window_s: float, min_samples: int) -> float:
            total, bad = self._ring.sums(now, window_s)
            if total < max(1, min_samples):
                return 0.0
            return (bad / total) / budget

        self.burn_fast = burn(self.cfg.fast_window_s, self.cfg.min_samples)
        self.burn_slow = burn(self.cfg.slow_window_s, self.cfg.min_samples)
        # Burn-idle clock: any burn above the idle threshold in EITHER
        # window re-arms it; idle_s is how long burn has stayed ~0 —
        # the controller's downscale gate (never shrink while burning).
        idle_max = getattr(self.cfg, "idle_burn_max", 0.1)
        if self.burn_fast > idle_max or self.burn_slow > idle_max:
            self._last_burn_ts = now
        was = self.violating
        self.violating = (self.burn_fast > self.cfg.burn_threshold
                          and self.burn_slow > self.cfg.burn_threshold)
        new_violation = self.violating and not was
        if new_violation:
            self.violations += 1
        try:
            g = _burn_gauge()
            g.set(self.burn_fast, tags={"Deployment": self.deployment,
                                        "Window": "fast"})
            g.set(self.burn_slow, tags={"Deployment": self.deployment,
                                        "Window": "slow"})
            if new_violation:
                _violations_counter().inc(
                    tags={"Deployment": self.deployment})
        except Exception:  # noqa: BLE001 — metrics must not fail control
            pass
        return {"fast": self.burn_fast, "slow": self.burn_slow,
                "violating": self.violating,
                "new_violation": new_violation,
                "idle_s": now - self._last_burn_ts}
