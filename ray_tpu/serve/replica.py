"""Replica actor: hosts one copy of a deployment's callable.

Reference parity: python/ray/serve/_private/replica.py (ReplicaActor :233,
UserCallableWrapper :715). Async ray_tpu actor with high max_concurrency;
tracks ongoing requests for the power-of-two router and autoscaler.
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
from typing import Any, Dict, Optional

_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None)


class RequestContext:
    def __init__(self, multiplexed_model_id: str = ""):
        self.multiplexed_model_id = multiplexed_model_id


def get_request_context() -> Optional[RequestContext]:
    return _request_context.get()


class ReplicaActor:
    def __init__(self, blob: bytes, user_config: Any = None):
        import cloudpickle
        spec = cloudpickle.loads(blob)
        func_or_class = spec["func_or_class"]
        init_args = spec["init_args"]
        init_kwargs = spec["init_kwargs"]
        # Resolve nested Applications to handles (deployment graphs).
        from ray_tpu.serve.handle import DeploymentHandle
        from ray_tpu.serve.deployment import Application

        def resolve(a):
            if isinstance(a, Application):
                return DeploymentHandle(a.deployment.name,
                                        app_name=spec["app_name"])
            return a

        init_args = tuple(resolve(a) for a in init_args)
        init_kwargs = {k: resolve(v) for k, v in init_kwargs.items()}
        if isinstance(func_or_class, type):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
            self._is_function = True
        self._ongoing = 0
        self._total = 0
        if user_config is not None:
            self._apply_user_config(user_config)

    def _apply_user_config(self, user_config):
        recon = getattr(self._callable, "reconfigure", None)
        if recon is None:
            raise ValueError(
                "user_config was set but the deployment has no "
                "reconfigure(user_config) method")
        res = recon(user_config)
        if inspect.iscoroutine(res):
            asyncio.ensure_future(res)

    async def reconfigure(self, user_config):
        self._apply_user_config(user_config)
        return True

    async def handle_request(self, method_name: str, mux_model_id: str,
                             args: tuple, kwargs: dict):
        self._ongoing += 1
        self._total += 1
        token = _request_context.set(RequestContext(mux_model_id))
        try:
            target = self._target_for(method_name)
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            _request_context.reset(token)
            self._ongoing -= 1

    def _target_for(self, method_name: str):
        if self._is_function or method_name in ("__call__", ""):
            return self._callable
        return getattr(self._callable, method_name)

    def is_streaming_method(self, method_name: str) -> bool:
        """True when the handler is a (sync or async) generator function —
        the proxy/handle use this to pick the streaming call path
        (reference: proxy.py checks the ASGI response type)."""
        target = self._target_for(method_name)
        fn = target if inspect.isfunction(target) or inspect.ismethod(
            target) else getattr(target, "__call__", target)
        return (inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn))

    async def handle_request_streaming(self, method_name: str,
                                       mux_model_id: str, args: tuple,
                                       kwargs: dict):
        """Streamed variant of handle_request: iterates the handler's
        generator, yielding each item as one stream element (delivered to
        the caller as a streaming-generator actor call)."""
        self._ongoing += 1
        self._total += 1
        token = _request_context.set(RequestContext(mux_model_id))
        try:
            target = self._target_for(method_name)
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            if inspect.isasyncgen(result):
                async for item in result:
                    yield item
            elif inspect.isgenerator(result):
                # Pull sync generators on the executor so a handler that
                # blocks between yields (sleep, model step) doesn't freeze
                # the replica loop (health checks, other requests). The
                # request context must travel to the executor thread:
                # run_in_executor submits the bare fn without contextvars,
                # which would break get_multiplexed_model_id() in the body.
                import contextvars
                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()

                def _next():
                    try:
                        return True, next(result)
                    except StopIteration:
                        return False, None

                while True:
                    ok, item = await loop.run_in_executor(
                        None, lambda: ctx.run(_next))
                    if not ok:
                        break
                    yield item
            else:
                yield result
        finally:
            _request_context.reset(token)
            self._ongoing -= 1

    def get_metrics(self) -> Dict[str, float]:
        return {"ongoing": self._ongoing, "total": self._total}

    async def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            res = user_check()
            if inspect.iscoroutine(res):
                res = await res
            return bool(res) if res is not None else True
        return True

    async def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful shutdown: wait for in-flight requests to finish."""
        deadline = asyncio.get_event_loop().time() + timeout_s
        while self._ongoing > 0:
            if asyncio.get_event_loop().time() > deadline:
                return False
            await asyncio.sleep(0.02)
        return True
