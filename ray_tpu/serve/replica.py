"""Replica actor: hosts one copy of a deployment's callable.

Reference parity: python/ray/serve/_private/replica.py (ReplicaActor :233,
UserCallableWrapper :715). Async ray_tpu actor with high max_concurrency;
tracks ongoing requests for the power-of-two router and autoscaler.

Request lifecycle hardening (serve-under-fire):

- **Admission control**: at most `max_ongoing` requests execute; up to
  `max_queued` more wait on the replica. Past that the request is shed
  immediately (drop-newest) with a typed BackPressureError — an
  overloaded deployment degrades to 503s instead of queueing unboundedly.
- **Deadlines**: the handle propagates the request's REMAINING time
  budget (converted to a local deadline on arrival — clock-skew-free
  across hosts); a request that is already late fails without
  executing, and an in-flight async handler is CANCELLED at the
  deadline so it stops burning TPU time.
- **Draining**: once `drain()` is called the replica stops admitting new
  work and hands every still-queued request back to the router with
  ReplicaDrainingError (queued work never started — replay-safe), then
  waits out in-flight requests within the graceful timeout.
- **Replay dedupe**: completed results are cached by request id so a
  replayed request (router re-route after a lost reply) returns the
  original result instead of executing twice — the replica-side half of
  exactly-once for `request_replay=True` deployments.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import inspect
import time
from typing import Any, Dict, Optional

from ray_tpu._private.flightrec import (RQ_ADMISSION, RQ_EXEC_END,
                                        RQ_EXEC_START, RQ_FIRST_ITEM,
                                        RQ_QUEUE_WAIT, RQ_REPLY)
from ray_tpu.serve import request_trace
from ray_tpu.serve.exceptions import (BackPressureError, ReplicaDrainingError,
                                      RequestTimeoutError, ServeError)

_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_context", default=None)

# Completed-result cache bound: old entries fall off FIFO. Sized so a
# burst of replays during one failover window always hits, without
# pinning unbounded result memory on a long-lived replica.
_DEDUPE_CAP = 2048


class RequestContext:
    def __init__(self, multiplexed_model_id: str = "",
                 deployment: str = ""):
        self.multiplexed_model_id = multiplexed_model_id
        self.deployment = deployment


def get_request_context() -> Optional[RequestContext]:
    return _request_context.get()


def _shed_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_shed_total",
        "serve requests dropped (drop-newest) by replica admission "
        "control: queue at max_queued_requests",
        tag_keys=("Deployment",))


def _timeout_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_timeouts_total",
        "serve requests that exceeded their end-to-end deadline "
        "(failed fast or cancelled on the replica)",
        tag_keys=("Deployment",))


class ReplicaActor:
    def __init__(self, blob: bytes, user_config: Any = None,
                 limits: Optional[dict] = None):
        import cloudpickle
        spec = cloudpickle.loads(blob)
        func_or_class = spec["func_or_class"]
        init_args = spec["init_args"]
        init_kwargs = spec["init_kwargs"]
        # Resolve nested Applications to handles (deployment graphs).
        from ray_tpu.serve.handle import DeploymentHandle
        from ray_tpu.serve.deployment import Application

        def resolve(a):
            if isinstance(a, Application):
                return DeploymentHandle(a.deployment.name,
                                        app_name=spec["app_name"])
            return a

        init_args = tuple(resolve(a) for a in init_args)
        init_kwargs = {k: resolve(v) for k, v in init_kwargs.items()}
        if isinstance(func_or_class, type):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
            self._is_function = True
        self._init_limits(limits)
        if user_config is not None:
            self._apply_user_config(user_config)
        # Event-loop lag visibility for a busy replica (once per hosting
        # process — co-resident serve daemons must not double-count).
        try:
            from ray_tpu.util import metrics
            metrics.start_loop_lag_probe_once("serve_replica")
        except Exception:  # noqa: BLE001 — no loop (bare unit tests)
            pass

    def _init_limits(self, limits: Optional[dict] = None):
        """Runtime request-path state (split out so unit tests can build
        a bare replica around an in-process callable)."""
        limits = limits or {}
        self._deployment = limits.get("deployment", "")
        self._max_ongoing = int(limits.get("max_ongoing", 100))
        self._max_queued = int(limits.get("max_queued", -1))
        # Result caching is the replica-side half of request replay; a
        # deployment that never replays (router fails fast instead) must
        # not pin dead results in memory.
        self._replay = bool(limits.get("request_replay", False))
        # SLO accounting (serve/slo.py inputs, polled via get_metrics):
        # counted for EVERY request — independent of trace sampling.
        self._slo_target = float(limits.get("slo_latency_target_s") or 0.0)
        self._ongoing = 0
        self._queued = 0
        self._total = 0
        self._completed = 0     # exec finished (success or app error)
        self._slow = 0          # completed OK but over the SLO target
        self._errors = 0        # handler raised a non-serve exception
        self._shed = 0
        self._timeouts = 0
        self._draining = False
        # Pulsed when a slot frees or drain flips: queued admits re-check.
        self._slot_event = asyncio.Event()
        self._dedupe: "collections.OrderedDict" = collections.OrderedDict()

    def _apply_user_config(self, user_config):
        recon = getattr(self._callable, "reconfigure", None)
        if recon is None:
            raise ValueError(
                "user_config was set but the deployment has no "
                "reconfigure(user_config) method")
        res = recon(user_config)
        if inspect.iscoroutine(res):
            asyncio.ensure_future(res)

    async def reconfigure(self, user_config):
        self._apply_user_config(user_config)
        return True

    # ------------------------------------------------------------------
    # Admission control + deadlines
    # ------------------------------------------------------------------
    def _count_shed(self):
        self._shed += 1
        try:
            _shed_counter().inc(tags={"Deployment": self._deployment})
        except Exception:  # noqa: BLE001 — metrics must not fail requests
            pass

    def _count_timeout(self):
        self._timeouts += 1
        try:
            _timeout_counter().inc(tags={"Deployment": self._deployment})
        except Exception:  # noqa: BLE001
            pass

    def _gate(self, deadline_ts: float):
        """Fail-fast checks before a request may queue/execute."""
        if self._draining:
            raise ReplicaDrainingError(self._deployment)
        if deadline_ts and time.time() >= deadline_ts:
            self._count_timeout()
            raise RequestTimeoutError(self._deployment, where="replica")

    async def _admit(self, deadline_ts: float):
        """Wait for an execution slot (reserved on return — the sync
        slot-claim after wakeup means two queued waiters can't both take
        the last slot); queued requests are bounded by max_queued (shed
        past it) and are handed BACK to the router the instant the
        replica starts draining — they never began executing, so
        re-routing them elsewhere is always safe."""
        self._gate(deadline_ts)
        if self._ongoing < self._max_ongoing:
            self._ongoing += 1
            return
        if 0 <= self._max_queued <= self._queued:
            self._count_shed()
            raise BackPressureError(self._deployment, self._queued,
                                    self._max_queued)
        self._queued += 1
        try:
            while self._ongoing >= self._max_ongoing:
                self._gate(deadline_ts)
                timeout = None
                if deadline_ts:
                    timeout = max(0.0, deadline_ts - time.time())
                self._slot_event.clear()
                try:
                    if timeout is None:
                        await self._slot_event.wait()
                    else:
                        await asyncio.wait_for(
                            self._slot_event.wait(), timeout + 0.001)
                except asyncio.TimeoutError:
                    pass
            self._gate(deadline_ts)
            self._ongoing += 1
        finally:
            self._queued -= 1

    def _release_slot(self):
        self._ongoing -= 1
        self._slot_event.set()

    async def _run_with_deadline(self, coro, deadline_ts: float):
        if not deadline_ts:
            return await coro
        remaining = deadline_ts - time.time()
        if remaining <= 0:
            coro.close()
            self._count_timeout()
            raise RequestTimeoutError(self._deployment, where="replica")
        try:
            return await asyncio.wait_for(coro, remaining)
        except asyncio.TimeoutError:
            self._count_timeout()
            raise RequestTimeoutError(
                self._deployment, timeout_s=remaining,
                where="replica (handler cancelled)") from None

    # ------------------------------------------------------------------
    # Request handling
    # ------------------------------------------------------------------
    def _trace_ctx(self, trace_ctx):
        if trace_ctx is None:
            return None
        try:
            ctx = request_trace.RequestTrace.from_wire(
                trace_ctx, self._deployment)
            # Bound on this hop for span()/the batch scheduler; nested
            # handle calls must mint their own child trace, not adopt it.
            ctx.replica_hop = True
            return ctx
        except Exception:  # noqa: BLE001 — tracing must not fail requests
            return None

    def _finish_request_trace(self, ctx):
        if ctx is None:
            return
        try:
            if ctx.phases[RQ_REPLY] is None:
                ctx.stamp(RQ_REPLY)
            request_trace.record_event(ctx, "replica",
                                       phases=list(ctx.phases))
        except Exception:  # noqa: BLE001
            pass

    def _account_exec(self, t0: float, error: bool):
        """SLO counters for one finished exec (disjoint categories: a
        failed handler counts as an error, never also as slow)."""
        self._completed += 1
        if error:
            self._errors += 1
        elif self._slo_target and time.time() - t0 > self._slo_target:
            self._slow += 1

    async def handle_request(self, method_name: str, mux_model_id: str,
                             args: tuple, kwargs: dict,
                             request_id: str = "",
                             timeout_s: float = 0.0,
                             trace_ctx=None):
        # The handle ships the REMAINING time budget, not an absolute
        # timestamp: converting to a local deadline here keeps the
        # semantics clock-skew-free across hosts (transit time is noise
        # next to ordinary NTP drift).
        deadline_ts = time.time() + timeout_s if timeout_s else 0.0
        # Constructor ran on the exec pool (no loop): the probe starts
        # with the first on-loop request instead. Set-hit after that.
        from ray_tpu.util.metrics import start_loop_lag_probe_once
        start_loop_lag_probe_once("serve_replica")
        ctx = self._trace_ctx(trace_ctx)
        if ctx is not None:
            ctx.stamp(RQ_ADMISSION)
        if self._replay and request_id and request_id in self._dedupe:
            # Replayed request whose original completed here: return the
            # cached result instead of executing twice (exactly-once) —
            # NO exec stamps/span, so a replayed trace keeps exactly one
            # exec span.
            self._finish_request_trace(ctx)
            return self._dedupe[request_id]
        try:
            await self._admit(deadline_ts)
        except BaseException:
            self._finish_request_trace(ctx)  # shed/drain/late visible
            raise
        if ctx is not None:
            ctx.stamp(RQ_QUEUE_WAIT)
        self._total += 1
        token = _request_context.set(
            RequestContext(mux_model_id, self._deployment))
        # Bind the trace to THIS task's contextvars: the user-facing
        # request_trace.span(...) API and the continuous-batching
        # scheduler both discover the active trace through current().
        rt_token = request_trace.bind(ctx)
        span = None
        if ctx is not None:
            span = request_trace.start_exec_span(
                ctx, f"exec:{self._deployment or method_name}")
        t0 = time.time()
        if ctx is not None:
            ctx.phases[RQ_EXEC_START] = t0
        try:
            target = self._target_for(method_name)
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await self._run_with_deadline(result, deadline_ts)
            if ctx is not None:
                ctx.stamp(RQ_EXEC_END)
            self._account_exec(t0, error=False)
            result = self._maybe_wrap_body(args, result)
            if self._replay and request_id:
                self._dedupe[request_id] = result
                while len(self._dedupe) > _DEDUPE_CAP:
                    self._dedupe.popitem(last=False)
            return result
        except ServeError as e:
            if ctx is not None:
                ctx.error = type(e).__name__
            raise  # deadline cancel: already in _timeouts
        except Exception as e:
            if ctx is not None:
                ctx.error = type(e).__name__
            self._account_exec(t0, error=True)
            raise
        finally:
            request_trace.finish_exec_span(span)
            self._finish_request_trace(ctx)
            request_trace.unbind(rt_token)
            _request_context.reset(token)
            self._release_slot()

    def _target_for(self, method_name: str):
        if self._is_function or method_name in ("__call__", ""):
            return self._callable
        return getattr(self._callable, method_name)

    @staticmethod
    def _maybe_wrap_body(args, result):
        """Route large HTTP response bodies through the object plane.

        Only for proxy-originated requests (Request.wrap_response): the
        bytes body serializes as an out-of-band buffer — one shm write
        here, a zero-copy view at the proxy — instead of being copied
        into and out of the reply frame. Direct handle.remote() callers
        see plain bytes, unchanged."""
        if not args:
            return result
        if not getattr(args[0], "wrap_response", False):
            return result
        if isinstance(result, (bytes, bytearray)):
            from ray_tpu._private import object_plane
            return object_plane.wrap_body(result)
        return result

    def is_streaming_method(self, method_name: str) -> bool:
        """True when the handler is a (sync or async) generator function —
        the proxy/handle use this to pick the streaming call path
        (reference: proxy.py checks the ASGI response type)."""
        target = self._target_for(method_name)
        fn = target if inspect.isfunction(target) or inspect.ismethod(
            target) else getattr(target, "__call__", target)
        return (inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn))

    async def handle_request_streaming(self, method_name: str,
                                       mux_model_id: str, args: tuple,
                                       kwargs: dict,
                                       request_id: str = "",
                                       timeout_s: float = 0.0,
                                       trace_ctx=None):
        """Streamed variant of handle_request: iterates the handler's
        generator, yielding each item as one stream element (delivered to
        the caller as a streaming-generator actor call). Shares the
        admission gate with the unary path; deadlines bound the wait for
        EACH item, cancelling a stalled async generator on the replica."""
        deadline_ts = time.time() + timeout_s if timeout_s else 0.0
        ctx = self._trace_ctx(trace_ctx)
        if ctx is not None:
            ctx.stamp(RQ_ADMISSION)
        try:
            await self._admit(deadline_ts)
        except BaseException:
            self._finish_request_trace(ctx)
            raise
        if ctx is not None:
            ctx.stamp(RQ_QUEUE_WAIT)
        self._total += 1
        token = _request_context.set(
            RequestContext(mux_model_id, self._deployment))
        rt_token = request_trace.bind(ctx)
        span = None
        if ctx is not None:
            span = request_trace.start_exec_span(
                ctx, f"exec:{self._deployment or method_name}")
        t_exec = time.time()
        if ctx is not None:
            ctx.phases[RQ_EXEC_START] = t_exec
        stream_error = False

        def _first_item():
            if ctx is not None and ctx.phases[RQ_FIRST_ITEM] is None:
                ctx.stamp(RQ_FIRST_ITEM)
        try:
            target = self._target_for(method_name)
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await self._run_with_deadline(result, deadline_ts)
            if inspect.isasyncgen(result):
                while True:
                    try:
                        item = await self._run_with_deadline(
                            result.__anext__(), deadline_ts)
                    except StopAsyncIteration:
                        break
                    _first_item()
                    yield self._maybe_wrap_body(args, item)
            elif inspect.isgenerator(result):
                # Pull sync generators on the executor so a handler that
                # blocks between yields (sleep, model step) doesn't freeze
                # the replica loop (health checks, other requests). The
                # request context must travel to the executor thread:
                # run_in_executor submits the bare fn without contextvars,
                # which would break get_multiplexed_model_id() in the body.
                import contextvars
                loop = asyncio.get_running_loop()
                cvars = contextvars.copy_context()

                def _next():
                    try:
                        return True, next(result)
                    except StopIteration:
                        return False, None

                while True:
                    if deadline_ts and time.time() >= deadline_ts:
                        self._count_timeout()
                        raise RequestTimeoutError(
                            self._deployment, where="replica (stream)")
                    ok, item = await loop.run_in_executor(
                        None, lambda: cvars.run(_next))
                    if not ok:
                        break
                    _first_item()
                    yield self._maybe_wrap_body(args, item)
            else:
                _first_item()
                yield result
        except ServeError:
            stream_error = True
            raise
        except (GeneratorExit, asyncio.CancelledError):
            stream_error = True  # caller went away: neither ok nor error
            raise
        except BaseException:
            stream_error = True
            self._account_exec(t_exec, error=True)
            raise
        finally:
            if not stream_error:
                if ctx is not None:
                    ctx.stamp(RQ_EXEC_END)
                self._account_exec(t_exec, error=False)
            request_trace.finish_exec_span(span)
            self._finish_request_trace(ctx)
            request_trace.unbind(rt_token)
            _request_context.reset(token)
            self._release_slot()

    def describe(self) -> Dict[str, Any]:
        """Process identity of this replica instance — lets operators
        (and the controller-recovery tests) prove a replica was
        REATTACHED, not restarted: the pid survives, a restart wouldn't."""
        import os
        return {"pid": os.getpid(), "deployment": self._deployment,
                "draining": self._draining}

    def get_metrics(self) -> Dict[str, Any]:
        out = {"ongoing": self._ongoing, "queued": self._queued,
               "total": self._total, "shed": self._shed,
               "timeouts": self._timeouts,
               "completed": self._completed, "slow": self._slow,
               "errors": self._errors,
               "draining": float(self._draining)}
        # Multiplexing: models currently resident in this replica's
        # @serve.multiplexed LRU cache(s). The controller polls this
        # with health and publishes it through the routing table so
        # handles can prefer model-resident replicas.
        resident = getattr(self._callable, "__serve_mux_resident__", None)
        if resident:
            out["resident_models"] = sorted(resident)
        return out

    async def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            res = user_check()
            if inspect.iscoroutine(res):
                res = await res
            return bool(res) if res is not None else True
        return True

    async def drain(self, timeout_s: float = 5.0,
                    linger_s: float = 0.0) -> bool:
        """Graceful shutdown: stop admitting, hand queued requests back
        to the router (ReplicaDrainingError — they re-route), wait for
        in-flight requests to finish within the timeout.

        linger_s keeps the (idle) replica alive PAST the last in-flight
        request: routers cache the routable set for up to REFRESH_S, so
        a request routed just before the set changed can still land here
        — during the linger it bounces with ReplicaDrainingError and
        re-routes; killing immediately would turn it into an
        ActorDiedError a non-replayable deployment cannot recover."""
        self._draining = True
        self._slot_event.set()  # wake queued admits so they bounce now
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout_s
        settle = loop.time() + linger_s
        while self._ongoing > 0 or loop.time() < settle:
            if loop.time() > deadline:
                return self._ongoing == 0
            await asyncio.sleep(0.02)
        return True
