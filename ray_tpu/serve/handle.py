"""DeploymentHandle + power-of-two-choices routing.

Reference parity: python/ray/serve/handle.py (DeploymentHandle) and
_private/replica_scheduler/pow_2_scheduler.py:44. The router keeps local
in-flight counts per replica and picks the lighter of two random choices —
locality/queue-aware without a round trip per request.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like result of handle.remote() (reference: DeploymentResponse).

    Sync callers (driver threads): wraps an ObjectRef; use .result().
    Async callers (replicas/proxy on the core loop): wraps a coroutine that
    performs routing + the call; use `await response`.
    """

    def __init__(self, ref=None, on_done=None, coro=None):
        self._ref = ref
        self._on_done = on_done or (lambda: None)
        self._coro = coro
        self._done = False

    def result(self, timeout: Optional[float] = None):
        if self._coro is not None:
            raise RuntimeError(
                "result() is not available in async context; use "
                "`await response` instead")
        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        finally:
            self._settle()
        return out

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def __del__(self):
        # Fire-and-forget callers never consume the response; settle on GC
        # so the router's in-flight counter doesn't leak and skew p2c.
        try:
            self._settle()
        except Exception:
            pass

    def __await__(self):
        if self._coro is not None:
            return self._coro.__await__()
        return self._awaitable(self._ref).__await__()

    async def _awaitable(self, ref):
        try:
            return await ref
        finally:
            self._settle()

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call's items (reference:
    handle.py DeploymentResponseGenerator). Yields VALUES; works as a sync
    iterator from driver threads and an async iterator on the core loop."""

    def __init__(self, ref_gen=None, on_done=None, setup_coro=None):
        self._gen = ref_gen
        self._on_done = on_done or (lambda: None)
        self._setup_coro = setup_coro  # async context: routing is deferred
        self._done = False

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def __iter__(self):
        return self

    def __next__(self):
        if self._gen is None:
            raise RuntimeError("streaming call was made in async context; "
                               "iterate with `async for`")
        try:
            ref = next(self._gen)
        except StopIteration:
            self._settle()
            raise
        return ray_tpu.get(ref)

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._gen is None:
            # First iteration in async context: run the deferred routing.
            self._gen, self._on_done = await self._setup_coro
        try:
            ref = await self._gen.__anext__()
        except StopAsyncIteration:
            self._settle()
            raise
        return await ref

    def __del__(self):
        try:
            self._settle()
        except Exception:
            pass


class Router:
    """Client-side replica picker with periodic replica-list refresh."""

    REFRESH_S = 1.0

    def __init__(self, deployment_name: str, app_name: str):
        self._dep = deployment_name
        self._app = app_name
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[int, int] = {}
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    def _apply(self, now, version, replicas):
        with self._lock:
            self._last_refresh = now
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {i: 0 for i in range(len(replicas))}

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_S:
            return
        from ray_tpu.serve.api import _get_controller
        ctrl = _get_controller()
        version, replicas = ray_tpu.get(
            ctrl.get_replicas.remote(self._app, self._dep), timeout=30)
        self._apply(now, version, replicas)

    async def refresh_async(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_S:
            return
        from ray_tpu.serve.api import _get_controller_async
        ctrl = await _get_controller_async()
        version, replicas = await ctrl.get_replicas.remote(
            self._app, self._dep)
        self._apply(now, version, replicas)

    def pick_cached(self):
        """Power of two choices on local in-flight counts (no refresh)."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self._dep!r} has no running replicas")
            if n == 1:
                i = 0
            else:
                a, b = random.sample(range(n), 2)
                i = a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) \
                    else b
            self._inflight[i] = self._inflight.get(i, 0) + 1
            return i, self._replicas[i]

    def pick(self):
        self._refresh()
        return self.pick_cached()

    def release(self, i: int):
        with self._lock:
            if i in self._inflight and self._inflight[i] > 0:
                self._inflight[i] -= 1

    def drop_replicas(self):
        with self._lock:
            self._version = -1
            self._last_refresh = 0.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method = method_name
        self._mux_id = multiplexed_model_id
        self._stream = stream
        self._router: Optional[Router] = None

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id,
            self._stream if stream is None else stream)
        h._router = self._router
        return h

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name, self.app_name)
        return self._router

    def remote(self, *args, **kwargs):
        import asyncio
        try:
            asyncio.get_running_loop()
            in_async = True
        except RuntimeError:
            in_async = False
        if in_async:
            # Replica/proxy context: routing must not block the loop.
            if self._stream:
                return DeploymentResponseGenerator(
                    setup_coro=self._stream_setup_async(args, kwargs))
            return DeploymentResponse(
                coro=self._call_async(args, kwargs))
        router = self._get_router()
        last_err = None
        for attempt in range(5):
            try:
                i, replica = router.pick()
            except RuntimeError as e:
                # Momentarily empty replica set (rolling update / health
                # replacement): force-refresh and retry.
                last_err = e
                router.drop_replicas()
                time.sleep(0.2 * (attempt + 1))
                continue
            try:
                if self._stream:
                    gen = replica.handle_request_streaming.options(
                        num_returns="streaming").remote(
                            self._method, self._mux_id, args, kwargs)
                    return DeploymentResponseGenerator(
                        gen, on_done=lambda i=i: router.release(i))
                ref = replica.handle_request.remote(
                    self._method, self._mux_id, args, kwargs)
                return DeploymentResponse(ref,
                                          on_done=lambda i=i: router.release(i))
            except Exception as e:
                router.release(i)
                router.drop_replicas()  # replica may be dead: force refresh
                last_err = e
        raise last_err

    async def _stream_setup_async(self, args, kwargs):
        """Deferred routing for a streaming call made on the core loop:
        returns (ObjectRefGenerator, release_fn)."""
        import asyncio
        router = self._get_router()
        last_err = None
        for attempt in range(5):
            await router.refresh_async(force=attempt > 0)
            try:
                i, replica = router.pick_cached()
            except RuntimeError as e:
                last_err = e
                router.drop_replicas()
                await asyncio.sleep(0.2 * (attempt + 1))
                continue
            try:
                gen = replica.handle_request_streaming.options(
                    num_returns="streaming").remote(
                        self._method, self._mux_id, args, kwargs)
                return gen, (lambda i=i: router.release(i))
            except Exception as e:  # noqa: BLE001
                router.release(i)
                router.drop_replicas()
                last_err = e
        raise last_err

    async def _call_async(self, args, kwargs):
        import asyncio
        from ray_tpu import exceptions as exc
        router = self._get_router()
        last_err = None
        for attempt in range(5):
            await router.refresh_async(force=attempt > 0)
            try:
                i, replica = router.pick_cached()
            except RuntimeError as e:
                last_err = e
                router.drop_replicas()
                await asyncio.sleep(0.2 * (attempt + 1))
                continue
            try:
                ref = replica.handle_request.remote(
                    self._method, self._mux_id, args, kwargs)
            except Exception as e:
                router.release(i)
                router.drop_replicas()
                last_err = e
                continue
            try:
                return await ref
            except exc.ActorDiedError as e:
                # Dead replica: refresh the set and retry. Application
                # exceptions propagate to the caller unchanged.
                router.drop_replicas()
                last_err = e
            finally:
                router.release(i)
        raise last_err

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method,
                 self._mux_id, self._stream))
