"""DeploymentHandle + power-of-two-choices routing with queue-preserving
failover.

Reference parity: python/ray/serve/handle.py (DeploymentHandle) and
_private/replica_scheduler/pow_2_scheduler.py:44. The router keeps local
in-flight counts per replica and picks the lighter of two random choices —
locality/queue-aware without a round trip per request.

Serve-under-fire semantics: the handle retains every dispatched request's
payload until its reply lands. When the replica dies (crash, slice
preemption) or hands queued work back while draining, the request is
re-routed to a healthy replica — gated on the deployment's
`request_replay` flag exactly like the RPC layer's idempotency replay:
replayable requests re-dispatch (deduped replica-side by request id),
non-replayable ones fail fast with a typed ReplicaDiedError. Requests a
draining replica handed back never started executing, so they re-route
unconditionally. End-to-end deadlines propagate handle -> replica: a
late request is cancelled ON the replica and surfaces as
RequestTimeoutError.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.flightrec import RQ_DISPATCH
from ray_tpu.serve import request_trace
from ray_tpu.serve.exceptions import (ReplicaDiedError, ReplicaDrainingError,
                                      RequestTimeoutError, ServeError, unwrap)

_MAX_ATTEMPTS = 6          # routing/replay attempts per request


def _replays_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_replays_total",
        "serve requests re-routed to a healthy replica after their "
        "replica died or drained (queue-preserving failover)",
        tag_keys=("Deployment",))


def _count_replay(deployment: str):
    try:
        _replays_counter().inc(tags={"Deployment": deployment})
    except Exception:  # noqa: BLE001 — metrics must not fail requests
        pass


class _PendingRequest:
    """Retained request payload: everything needed to re-dispatch."""

    __slots__ = ("method", "mux_id", "args", "kwargs", "request_id",
                 "deadline_ts", "attempts", "trace", "finish_on_settle",
                 "last_rid")

    def __init__(self, method: str, mux_id: str, args: tuple, kwargs: dict,
                 deadline_ts: float = 0.0, trace=None):
        self.finish_on_settle = False
        self.method = method
        self.mux_id = mux_id
        self.args = args
        self.kwargs = kwargs
        # Trace context rides the request: proxy-minted (contextvar) or
        # handle-minted here. The replay-dedupe key stays a PRIVATE
        # uuid4 — the trace id may be client-supplied (X-Request-Id),
        # and a reused client id must never alias two requests onto one
        # replica result-cache entry.
        self.trace = trace
        self.request_id = uuid.uuid4().hex
        self.deadline_ts = deadline_ts
        self.attempts = 0
        self.last_rid = None   # replica this request last dispatched to

    def wire_trace(self):
        return self.trace.wire() \
            if self.trace is not None and self.trace.sampled else None

    def record_replay(self, err) -> None:
        if self.trace is not None:
            try:
                self.trace.record_replay(repr(err))
            except Exception:  # noqa: BLE001 — tracing never fails calls
                pass

    def settle_trace(self) -> None:
        """Finish a HANDLE-minted trace when the response settles (a
        proxy-minted one is finished by the proxy, which also stamps the
        reply phase after the payload went out on the socket)."""
        if self.trace is not None and self.finish_on_settle:
            try:
                request_trace.finish(self.trace, "handle")
            except Exception:  # noqa: BLE001
                pass


class DeploymentResponse:
    """Future-like result of handle.remote() (reference: DeploymentResponse).

    Sync callers (driver threads): wraps an ObjectRef; use .result().
    Async callers (replicas/proxy on the core loop): wraps a coroutine that
    performs routing + the call; use `await response`.
    """

    def __init__(self, ref=None, on_done=None, coro=None, recover=None):
        self._ref = ref
        self._on_done = on_done or (lambda: None)
        self._coro = coro
        self._recover = recover  # fn(err) -> new ref (re-dispatch) or raise
        self._done = False

    def result(self, timeout: Optional[float] = None):
        if self._coro is not None:
            raise RuntimeError(
                "result() is not available in async context; use "
                "`await response` instead")
        from ray_tpu import exceptions as exc
        while True:
            try:
                out = ray_tpu.get(self._ref, timeout=timeout)
                self._settle()
                return out
            except exc.TaskError as e:
                cause = unwrap(e)
                if isinstance(cause, ReplicaDrainingError) \
                        and self._recover is not None:
                    # Queued work handed back by a draining replica:
                    # always replay-safe (it never started executing).
                    try:
                        self._ref = self._recover(cause)
                        continue
                    except Exception:
                        self._settle()
                        raise
                if isinstance(cause, ServeError):
                    self._settle()
                    raise cause from None   # typed errors surface bare
                self._settle()
                raise
            except (exc.ActorDiedError, exc.ActorUnavailableError,
                    exc.WorkerCrashedError) as e:
                if self._recover is None:
                    self._settle()
                    raise
                try:
                    self._ref = self._recover(e)
                except Exception:
                    self._settle()
                    raise
            except Exception:
                self._settle()
                raise

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def __del__(self):
        # Fire-and-forget callers never consume the response; settle on GC
        # so the router's in-flight counter doesn't leak and skew p2c.
        try:
            self._settle()
        except Exception:
            pass

    def __await__(self):
        if self._coro is not None:
            return self._coro.__await__()
        return self._awaitable(self._ref).__await__()

    async def _awaitable(self, ref):
        try:
            return await ref
        finally:
            self._settle()

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call's items (reference:
    handle.py DeploymentResponseGenerator). Yields VALUES; works as a sync
    iterator from driver threads and an async iterator on the core loop.

    Failover: before the FIRST item, a died/draining replica re-routes
    the stream (replay-gated like unary calls). After items were
    delivered, a REPLAYABLE deployment re-routes with a mid-stream
    cursor: the handle tracks the item offset already delivered, replays
    the stream on a healthy replica, and fast-forwards past the cursor —
    the caller sees the stream resume from the last delivered item, no
    duplicates, no restart. (The handler re-executes, so this is gated
    on `request_replay=True` exactly like unary replays; a replay that
    produces FEWER items than the cursor — a non-deterministic handler —
    fails with a typed ReplicaDiedError instead of silently yielding a
    divergent tail.) Non-replayable deployments keep the old behavior:
    a typed ReplicaDiedError after the first delivered item."""

    def __init__(self, ref_gen=None, on_done=None, setup_coro=None,
                 recover=None, deployment: str = ""):
        self._gen = ref_gen
        self._on_done = on_done or (lambda: None)
        self._setup_coro = setup_coro  # async context: routing is deferred
        self._recover = recover        # sync re-dispatch (replay-gated)
        self._deployment = deployment
        self._items = 0
        self._to_skip = 0              # replay cursor fast-forward budget
        self._done = False

    def _settle(self):
        if not self._done:
            self._done = True
            self._on_done()

    def _release_once(self):
        """Release the current replica slot exactly once before a replay
        re-setup: a later _settle()/__del__ must not double-decrement the
        router's in-flight count if the re-setup raises."""
        cb, self._on_done = self._on_done, (lambda: None)
        cb()

    def __iter__(self):
        return self

    def _short_replay(self):
        self._settle()
        return ReplicaDiedError(
            self._deployment,
            reason=f"mid-stream replay ended after "
                   f"{self._items - self._to_skip} item(s), before the "
                   f"{self._items}-item cursor — handler output is not "
                   f"deterministic, cannot resume the stream")

    def _recover_sync(self, err):
        """Replay-gated re-route (sync path): on success the cursor arms
        the fast-forward so already-delivered items are skipped."""
        if self._recover is None:
            self._settle()
            raise err
        try:
            self._gen = self._recover(err)
        except BaseException:
            self._settle()
            raise
        self._to_skip = self._items

    def __next__(self):
        if self._gen is None:
            raise RuntimeError("streaming call was made in async context; "
                               "iterate with `async for`")
        from ray_tpu import exceptions as exc
        while True:
            try:
                try:
                    ref = next(self._gen)
                except StopIteration:
                    if self._to_skip > 0:
                        raise self._short_replay() from None
                    self._settle()
                    raise
                value = ray_tpu.get(ref)
                if self._to_skip > 0:
                    self._to_skip -= 1   # cursor fast-forward: re-
                    continue             # delivered item, don't re-yield
                self._items += 1
                return value
            except exc.TaskError as e:
                cause = unwrap(e)
                if isinstance(cause, ReplicaDrainingError) \
                        and self._recover is not None:
                    # Pre-first-item: always replay-safe. Mid-replay
                    # bounce (re-routed onto a now-draining replica):
                    # gated inside _recover like any replay.
                    self._recover_sync(cause)
                    continue
                if isinstance(cause, ServeError):
                    self._settle()
                    raise cause from None
                self._settle()
                raise
            except (exc.ActorDiedError, exc.ActorUnavailableError,
                    exc.WorkerCrashedError) as e:
                if self._recover is not None:
                    try:
                        # Replay-gated (request_replay): items == 0 always
                        # re-routes; past that the cursor resumes.
                        self._recover_sync(e)
                        continue
                    except ReplicaDiedError:
                        raise
                    except (exc.ActorDiedError, exc.ActorUnavailableError,
                            exc.WorkerCrashedError):
                        raise ReplicaDiedError(
                            self._deployment,
                            reason=f"died mid-stream after {self._items} "
                                   f"item(s)") from e
                self._settle()
                raise ReplicaDiedError(
                    self._deployment,
                    reason=f"died mid-stream after {self._items} item(s)",
                ) from e

    def __aiter__(self):
        return self

    async def _recover_async(self, err):
        """Replay-gated re-route (async path) + cursor arm."""
        if self._setup_coro is None:
            self._settle()
            raise err
        self._release_once()
        try:
            self._gen, self._on_done = await self._setup_coro(err)
        except BaseException:
            self._settle()
            raise
        self._to_skip = self._items

    async def __anext__(self):
        from ray_tpu import exceptions as exc
        if self._gen is None:
            # First iteration in async context: run the deferred routing.
            self._gen, self._on_done = await self._setup_coro(None)
        while True:
            try:
                ref = await self._gen.__anext__()
                value = await ref
                if self._to_skip > 0:
                    self._to_skip -= 1   # cursor fast-forward
                    continue
                self._items += 1
                return value
            except StopAsyncIteration:
                if self._to_skip > 0:
                    raise self._short_replay() from None
                self._settle()
                raise
            except exc.TaskError as e:
                cause = unwrap(e)
                if isinstance(cause, ReplicaDrainingError) \
                        and self._setup_coro is not None:
                    await self._recover_async(cause)
                    continue
                if isinstance(cause, ServeError):
                    self._settle()
                    raise cause from None
                self._settle()
                raise
            except (exc.ActorDiedError, exc.ActorUnavailableError,
                    exc.WorkerCrashedError) as e:
                if self._setup_coro is not None:
                    try:
                        # Replay-gated inside the setup: non-replayable
                        # deployments get the typed ReplicaDiedError here
                        # (items == 0 always re-routes; past that the
                        # cursor resumes on a replayable deployment).
                        await self._recover_async(e)
                        continue
                    except ReplicaDiedError:
                        raise
                    except (exc.ActorDiedError, exc.ActorUnavailableError,
                            exc.WorkerCrashedError):
                        raise ReplicaDiedError(
                            self._deployment,
                            reason=f"died mid-stream after {self._items} "
                                   f"item(s)") from e
                self._settle()
                raise ReplicaDiedError(
                    self._deployment,
                    reason=f"died mid-stream after {self._items} item(s)",
                ) from e

    def __del__(self):
        try:
            self._settle()
        except Exception:
            pass


class Router:
    """Client-side replica picker with periodic replica-list refresh.

    Replicas are keyed by the controller-issued replica id; in-flight
    counts survive list refreshes for replicas that stay in the set.

    Stale-while-revalidate: when the controller is unreachable (crash,
    restart, recovery in progress) the router keeps serving from its
    last-known routing table for up to STALE_MAX_S — a controller death
    alone never fails a request. Locally-observed replica deaths/drains
    evict the replica from the cached set (`evict`) so stale routing
    converges onto the live replicas without the controller's help."""

    REFRESH_S = 1.0
    # Bounded staleness: past this with no successful controller round
    # trip the cached routing is too old to trust (replicas may have
    # moved wholesale) and routing errors surface to the caller.
    STALE_MAX_S = 30.0

    def __init__(self, deployment_name: str, app_name: str):
        self._dep = deployment_name
        self._app = app_name
        self._replicas: List[Tuple[str, Any]] = []   # [(replica_id, handle)]
        self._version = -1
        self._inflight: Dict[str, int] = {}
        self._meta: Dict[str, Any] = {}
        # Multiplexing: replica_id -> frozenset of resident model ids,
        # published by the controller (polled from replicas with health).
        self._resident: Dict[str, frozenset] = {}
        self._last_refresh = 0.0       # last refresh ATTEMPT (throttle)
        self._last_success = 0.0       # last controller round trip
        self._lock = threading.Lock()

    @property
    def meta(self) -> Dict[str, Any]:
        return self._meta

    @property
    def replayable(self) -> bool:
        return bool(self._meta.get("request_replay"))

    def _apply(self, now, routing: dict):
        with self._lock:
            self._last_refresh = now
            self._last_success = now
            self._meta = routing.get("config") or self._meta
            version = routing.get("version", 0)
            if version != self._version:
                self._version = version
                self._replicas = list(routing.get("replicas") or [])
                self._resident = {
                    rid: frozenset(models) for rid, models in
                    (routing.get("resident") or {}).items()}
                old = self._inflight
                self._inflight = {rid: old.get(rid, 0)
                                  for rid, _ in self._replicas}

    def _serve_stale(self, now, err) -> None:
        """Refresh failed (controller down/restarting): keep the cached
        set within the staleness bound, surface the error past it."""
        if self._replicas and now - self._last_success < self.STALE_MAX_S:
            return
        raise err

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_S:
            return
        self._last_refresh = now
        from ray_tpu.serve.api import _get_controller
        ctrl = _get_controller()
        try:
            routing = ray_tpu.get(
                ctrl.get_routing.remote(self._app, self._dep), timeout=10)
        except Exception as e:  # noqa: BLE001 — stale-while-revalidate
            self._serve_stale(now, e)
            return
        self._apply(now, routing)

    async def refresh_async(self, force: bool = False):
        import asyncio
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_S:
            return
        self._last_refresh = now
        try:
            from ray_tpu.serve.api import _get_controller_async
            ctrl = await _get_controller_async()
            routing = await asyncio.wait_for(
                ctrl.get_routing.remote(self._app, self._dep).future(),
                timeout=10)
        except Exception as e:  # noqa: BLE001 — stale-while-revalidate
            self._serve_stale(now, e)
            return
        self._apply(now, routing)

    def pick_cached(self, mux_id: str = ""):
        """Power of two choices on local in-flight counts (no refresh).

        Multiplex-aware: a request tagged with a model id picks among
        the replicas where that model is already RESIDENT (p2c within
        the subset — locality never defeats load balancing between
        warm replicas); only when no replica holds the model does it
        fall back to plain p2c over the full set, and the chosen
        replica's LRU loads the model (becoming resident for the next
        routing refresh)."""
        with self._lock:
            pool = list(range(len(self._replicas)))
            if not pool:
                raise RuntimeError(
                    f"deployment {self._dep!r} has no running replicas")
            if mux_id:
                warm = [i for i in pool
                        if mux_id in self._resident.get(
                            self._replicas[i][0], ())]
                if warm:
                    pool = warm
            n = len(pool)
            if n == 1:
                i = pool[0]
            else:
                a, b = random.sample(pool, 2)
                i = a if self._inflight.get(self._replicas[a][0], 0) <= \
                    self._inflight.get(self._replicas[b][0], 0) else b
            rid, handle = self._replicas[i]
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
            return rid, handle

    def pick(self, mux_id: str = ""):
        self._refresh()
        return self.pick_cached(mux_id)

    def release(self, rid: str):
        with self._lock:
            if rid in self._inflight and self._inflight[rid] > 0:
                self._inflight[rid] -= 1

    def evict(self, rid: str):
        """Locally remove a replica the caller OBSERVED dead/draining:
        during a controller outage the stale routing table can't drop it
        for us, and p2c would keep burning attempts on the corpse. The
        next successful controller refresh replaces the whole set."""
        with self._lock:
            before = len(self._replicas)
            self._replicas = [(r, h) for r, h in self._replicas if r != rid]
            if len(self._replicas) != before:
                self._inflight.pop(rid, None)
                self._version = -1   # any refresh re-applies authoritative

    def drop_replicas(self):
        with self._lock:
            self._version = -1
            self._last_refresh = 0.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 timeout_s: Optional[float] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method = method_name
        self._mux_id = multiplexed_model_id
        self._stream = stream
        self._timeout_s = timeout_s
        self._router: Optional[Router] = None

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                timeout_s: Optional[float] = None) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id,
            self._stream if stream is None else stream,
            self._timeout_s if timeout_s is None else timeout_s)
        # Share a MATERIALIZED router: proxies derive a per-request
        # handle via options(multiplexed_model_id=...) — copying a
        # still-None router would hand every derived handle its own
        # fresh Router (a controller round trip per request, p2c over
        # empty in-flight counts).
        h._router = self._get_router()
        return h

    def _get_router(self) -> Router:
        if self._router is None:
            self._router = Router(self.deployment_name, self.app_name)
        return self._router

    # ------------------------------------------------------------------
    # Request construction + replay gating
    # ------------------------------------------------------------------
    def _make_request(self, args, kwargs) -> _PendingRequest:
        deadline = time.time() + self._timeout_s if self._timeout_s else 0.0
        # Request trace: adopt the ingress context (proxy set it on this
        # task's contextvars) or mint one here — EVERY entry into the
        # serve data plane carries a request id + trace from this point.
        # The REPLICA hop's bound context is not ours to adopt: a nested
        # handle call mints a CHILD trace (inheriting the trace id
        # through the active exec span) instead of stamping dispatch
        # into the replica's phase record.
        ctx = request_trace.current()
        if ctx is not None and ctx.replica_hop:
            ctx = None
        handle_minted = False
        if ctx is None:
            try:
                ctx = request_trace.mint(self.deployment_name, hop="handle")
                handle_minted = True
            except Exception:  # noqa: BLE001 — tracing never fails calls
                ctx = None
        req = _PendingRequest(self._method, self._mux_id, args, kwargs,
                              deadline_ts=deadline, trace=ctx)
        # A proxy-minted context is recorded/finished by the proxy; the
        # handle finishes only traces it minted itself.
        req.finish_on_settle = handle_minted
        return req

    def _fill_deadline(self, req: _PendingRequest, router: Router):
        """Apply the deployment's default request_timeout_s (known only
        after the first routing refresh) when no per-call timeout set."""
        if req.deadline_ts:
            return
        default = router.meta.get("request_timeout_s")
        if default:
            req.deadline_ts = time.time() + float(default)

    def _gate_replay(self, router: Router, req: _PendingRequest, err):
        """Decide whether a failed dispatch may re-route. Raises the
        caller-facing typed error when it may not."""
        if req.deadline_ts and time.time() >= req.deadline_ts:
            raise RequestTimeoutError(self.deployment_name,
                                      where="router") from err
        if req.attempts >= _MAX_ATTEMPTS:
            raise ReplicaDiedError(
                self.deployment_name,
                reason=f"gave up after {req.attempts} attempts: {err!r}",
            ) from err
        if isinstance(err, ReplicaDrainingError):
            return  # handed back before execution: always replay-safe
        if not router.replayable:
            raise ReplicaDiedError(self.deployment_name,
                                   reason=repr(err)) from err

    @staticmethod
    def _remaining(req: _PendingRequest) -> float:
        """Time budget left, shipped to the replica INSTEAD of the
        absolute deadline: the replica re-anchors it on its own clock,
        so cross-host clock skew cannot corrupt deadline semantics."""
        if not req.deadline_ts:
            return 0.0
        return max(0.001, req.deadline_ts - time.time())

    @staticmethod
    def _stamp_dispatch(req: _PendingRequest):
        """Request-trace dispatch stamp + the wire context forwarded to
        the replica (None when unsampled — zero overhead off)."""
        if req.trace is None:
            return None
        if req.trace.sampled:
            req.trace.stamp(RQ_DISPATCH)
        return req.wire_trace()

    def _submit(self, replica, req: _PendingRequest):
        trace_ctx = self._stamp_dispatch(req)
        return replica.handle_request.remote(
            req.method, req.mux_id, req.args, req.kwargs,
            req.request_id, self._remaining(req), trace_ctx)

    def _submit_stream(self, replica, req: _PendingRequest):
        trace_ctx = self._stamp_dispatch(req)
        return replica.handle_request_streaming.options(
            num_returns="streaming").remote(
                req.method, req.mux_id, req.args, req.kwargs,
                req.request_id, self._remaining(req), trace_ctx)

    # ------------------------------------------------------------------
    # Sync (driver-thread) path
    # ------------------------------------------------------------------
    def remote(self, *args, **kwargs):
        import asyncio
        try:
            asyncio.get_running_loop()
            in_async = True
        except RuntimeError:
            in_async = False
        req = self._make_request(args, kwargs)
        if in_async:
            # Replica/proxy context: routing must not block the loop.
            if self._stream:
                return DeploymentResponseGenerator(
                    setup_coro=lambda err: self._stream_setup_async(req, err),
                    deployment=self.deployment_name)
            return DeploymentResponse(coro=self._call_async(req))
        router = self._get_router()
        state = {"rid": None}

        def release():
            rid, state["rid"] = state["rid"], None
            if rid is not None:
                router.release(rid)

        submit = self._submit_stream if self._stream else self._submit

        def dispatch():
            last_err = None
            for attempt in range(5):
                if req.deadline_ts and time.time() >= req.deadline_ts:
                    raise RequestTimeoutError(self.deployment_name,
                                              where="router")
                try:
                    rid, replica = router.pick(req.mux_id)
                except RuntimeError as e:
                    # Momentarily empty replica set (rolling update /
                    # health replacement): force-refresh and retry.
                    last_err = e
                    router.drop_replicas()
                    time.sleep(0.2 * (attempt + 1))
                    continue
                # pick() refreshed routing: the deployment's default
                # request_timeout_s is known — stamp the deadline BEFORE
                # the payload ships.
                self._fill_deadline(req, router)
                try:
                    out = submit(replica, req)
                    state["rid"] = rid
                    req.last_rid = rid
                    return out
                except Exception as e:
                    router.release(rid)
                    router.drop_replicas()  # replica may be dead: refresh
                    last_err = e
            raise last_err

        def recover(err):
            failed_rid = state["rid"]
            release()
            req.attempts += 1
            self._gate_replay(router, req, err)
            _count_replay(self.deployment_name)
            req.record_replay(err)  # failover stays ONE trace: replay hop
            # Locally evict the observed-dead/draining replica: during a
            # controller outage the stale routing table can't drop it.
            if failed_rid is not None:
                router.evict(failed_rid)
            router.drop_replicas()
            # Backoff: the controller needs a health-check round to drop
            # a dead replica from the routable set — instant re-dispatch
            # could burn every attempt on the same corpse.
            if not isinstance(err, ReplicaDrainingError):
                time.sleep(min(0.25 * req.attempts, 1.0))
            return dispatch()

        def done():
            release()
            req.settle_trace()

        first = dispatch()
        if self._stream:
            return DeploymentResponseGenerator(
                first, on_done=done, recover=recover,
                deployment=self.deployment_name)
        return DeploymentResponse(first, on_done=done, recover=recover)

    # ------------------------------------------------------------------
    # Async (core-loop) paths
    # ------------------------------------------------------------------
    async def _stream_setup_async(self, req: _PendingRequest, err=None):
        """Deferred routing for a streaming call made on the core loop:
        returns (ObjectRefGenerator, release_fn). Re-invoked by the
        generator for pre-first-item failover with the triggering error —
        each re-invocation is gated on the replay rules."""
        import asyncio
        router = self._get_router()
        if err is not None:
            req.attempts += 1
            self._gate_replay(router, req, err)
            _count_replay(self.deployment_name)
            req.record_replay(err)
            if req.last_rid is not None:
                router.evict(req.last_rid)
            router.drop_replicas()
            if not isinstance(err, ReplicaDrainingError):
                # Let the controller's health check drop the dead replica.
                await asyncio.sleep(min(0.25 * req.attempts, 1.0))
        last_err = None
        for attempt in range(5):
            await router.refresh_async(force=attempt > 0 or err is not None)
            self._fill_deadline(req, router)
            if req.deadline_ts and time.time() >= req.deadline_ts:
                raise RequestTimeoutError(self.deployment_name,
                                          where="router")
            try:
                rid, replica = router.pick_cached(req.mux_id)
            except RuntimeError as e:
                last_err = e
                router.drop_replicas()
                await asyncio.sleep(0.2 * (attempt + 1))
                continue
            try:
                req.last_rid = rid
                gen = self._submit_stream(replica, req)

                def _release(rid=rid):
                    router.release(rid)
                    req.settle_trace()
                return gen, _release
            except Exception as e:  # noqa: BLE001
                router.release(rid)
                router.drop_replicas()
                last_err = e
        raise last_err

    async def _call_async(self, req: _PendingRequest):
        try:
            return await self._call_async_inner(req)
        finally:
            req.settle_trace()

    async def _call_async_inner(self, req: _PendingRequest):
        import asyncio
        from ray_tpu import exceptions as exc
        router = self._get_router()
        last_err = None
        while True:
            if req.attempts >= _MAX_ATTEMPTS:
                raise ReplicaDiedError(
                    self.deployment_name,
                    reason=f"gave up after {req.attempts} attempts",
                ) from last_err
            req.attempts += 1
            await router.refresh_async(force=last_err is not None)
            self._fill_deadline(req, router)
            if req.deadline_ts and time.time() >= req.deadline_ts:
                raise RequestTimeoutError(self.deployment_name,
                                          where="router") from last_err
            try:
                rid, replica = router.pick_cached(req.mux_id)
            except RuntimeError as e:
                last_err = e
                router.drop_replicas()
                await asyncio.sleep(min(0.2 * req.attempts, 1.0))
                continue
            try:
                ref = self._submit(replica, req)
            except Exception as e:
                router.release(rid)
                router.drop_replicas()
                last_err = e
                continue
            try:
                return await ref
            except exc.TaskError as e:
                cause = unwrap(e)
                if isinstance(cause, ReplicaDrainingError):
                    # Handed back before execution: re-route, always.
                    router.evict(rid)
                    router.drop_replicas()
                    _count_replay(self.deployment_name)
                    req.record_replay(cause)
                    last_err = cause
                    continue
                if isinstance(cause, ServeError):
                    raise cause from None
                raise    # application exceptions propagate unchanged
            except (exc.ActorDiedError, exc.ActorUnavailableError,
                    exc.WorkerCrashedError) as e:
                router.evict(rid)
                router.drop_replicas()
                if not router.replayable:
                    raise ReplicaDiedError(self.deployment_name,
                                           reason=repr(e)) from e
                _count_replay(self.deployment_name)
                req.record_replay(e)
                last_err = e
                # Backoff past the controller's health-check round so
                # retries don't all land on the not-yet-dropped corpse.
                await asyncio.sleep(min(0.25 * req.attempts, 1.0))
            finally:
                router.release(rid)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method,
                 self._mux_id, self._stream, self._timeout_s))
