"""WebSocket support for the Serve proxy.

Reference parity: python/ray/serve supports websocket endpoints through
its ASGI/starlette integration (serve._private.proxy handles the ASGI
`websocket` scope). Here the proxy speaks RFC 6455 directly (no external
deps): it performs the upgrade handshake, decodes masked client frames,
and bridges a duplex session to the replica —

  * server -> client: the deployment handler is an async generator; each
    yielded str/bytes becomes a text/binary frame the moment it is
    produced (same streaming path as chunked HTTP).
  * client -> server: the handler awaits `request.ws.receive()`, which
    long-polls the PROXY actor (the socket owner) for the next message
    through a normal actor call.

Unfragmented messages only (fin=1), which covers every common client;
pings are answered by the proxy, close frames end the session.
"""

from __future__ import annotations

import base64
import hashlib
import os
import struct
from typing import Optional, Tuple

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0D21AD85"

OP_CONT, OP_TEXT, OP_BINARY, OP_CLOSE, OP_PING, OP_PONG = (
    0x0, 0x1, 0x2, 0x8, 0x9, 0xA)

# Ingress DoS guard (ADVICE r4): the 64-bit length field is
# client-controlled; without a cap a single frame header makes the proxy
# attempt an arbitrarily large allocation. Overridable for legit
# big-message deployments.
MAX_FRAME_PAYLOAD = int(os.environ.get(
    "RAY_TPU_SERVE_WS_MAX_FRAME", 8 * 1024 * 1024))


class FrameTooLarge(Exception):
    """Client declared a frame above MAX_FRAME_PAYLOAD; close with 1009."""

    def __init__(self, n: int):
        super().__init__(
            f"websocket frame of {n} bytes exceeds the "
            f"{MAX_FRAME_PAYLOAD}-byte limit")
        self.declared = n


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    """One unfragmented frame. Servers send unmasked; clients MUST mask."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < (1 << 16):
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


async def read_frame(reader) -> Tuple[int, bytes]:
    """-> (opcode, payload); unmasks client frames."""
    b0, b1 = await reader.readexactly(2)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", await reader.readexactly(2))
    elif n == 127:
        (n,) = struct.unpack(">Q", await reader.readexactly(8))
    if n > MAX_FRAME_PAYLOAD:
        raise FrameTooLarge(n)
    key = await reader.readexactly(4) if masked else None
    payload = await reader.readexactly(n) if n else b""
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, payload


class WebSocketChannel:
    """Replica-side receive channel: `request.ws` in a websocket handler.

    Wraps the proxy actor handle + connection id; receive() long-polls
    the proxy for the next client message. Returns None when the client
    closed."""

    def __init__(self, proxy_handle, conn_id: str):
        self._proxy = proxy_handle
        self._conn_id = conn_id

    async def receive(self, timeout: Optional[float] = None):
        """Next client message; None when the client CLOSED. An idle
        client past `timeout` raises TimeoutError instead (so a handler
        can keep the session alive through silence)."""
        out = await self._proxy.ws_receive.remote(self._conn_id, timeout)
        if out.get("closed"):
            return None
        if out.get("timeout"):
            raise TimeoutError(
                f"no websocket message within {timeout}s")
        return out["msg"]

    def __reduce__(self):
        return (WebSocketChannel, (self._proxy, self._conn_id))
