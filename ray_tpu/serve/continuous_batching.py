"""Continuous (iteration-level) batching: the serve inference hot path.

`@serve.batch` (serve/batching.py) is queue-then-flush: calls coalesce
into ONE fixed batch, the whole batch runs, the whole batch returns.
That shape starves a TPU the moment sequence lengths diverge — the
jitted decode step idles while the longest sequence finishes. This
module is the iteration-level engine the Gemma-on-TPU serving paper
builds around: requests JOIN a running batch at step boundaries, every
finished sequence RETIRES mid-flight and its slot backfills from the
admission queue on the next boundary, so the step function stays fed at
high occupancy for as long as there is work.

Scheduler contract (the user's decorated method is the STEP function):

    @serve.deployment
    class LM:
        @serve.continuous_batching(max_batch_size=8)
        def step(self, phase, batch):
            # phase: "prefill" | "decode"
            # batch: list of EXACTLY max_batch_size slots — Sequence
            #   objects for live slots, None for padding. The length
            #   never changes, so a jitted callable traced on the first
            #   step never recompiles (pad-to-bucket).
            # returns: a list of the same length; None for pad slots,
            #   (emission, done) for live ones. emission=None emits
            #   nothing this step; after its prefill step a sequence
            #   moves to the decode phase unless done.
            ...

        async def __call__(self, prompt):
            async for token in self.step(prompt):   # submit ONE request
                yield token

    Calling the wrapped step with one request's args submits it to the
    per-instance BatchScheduler and returns an async generator of that
    request's emissions — which composes with the replica streaming
    path, so tokens flow to the client as the batch produces them and a
    replica death mid-generation fails over through the handle's
    mid-stream replay cursor (PR 10) with zero client-visible loss.

Scheduling policy:

- Prefill and decode are DISTINCT scheduled phases: a step runs either
  up to ``prefill_chunk`` prefill-phase sequences or every decode-ready
  sequence, never a mix — matching the two jitted callables a TPU
  serving stack actually has.
- Prefill has priority (time-to-first-token), bounded by
  ``decode_starvation_steps``: after that many consecutive prefill
  steps with decode work waiting, one decode step is forced so a
  prefill flood can never stall token streams already in flight.
- Multiplexed tenancy: each step groups sequences of ONE model id
  (oldest-waiting model first), so a replica hosting several
  ``@serve.multiplexed`` models never thrashes its LRU by interleaving
  models within a step.

Observability: per-sequence REQ_* stamps (``prefill_end`` marks the
prefill->decode transition on the request's trace) plus ``prefill`` /
``decode`` spans under the replica exec span, and two histograms —
``ray_tpu_serve_batch_occupancy`` (live slots per step) and
``ray_tpu_serve_batch_step_seconds{Phase=prefill|decode}``.
"""

from __future__ import annotations

import asyncio
import functools
import os
import time
from collections import deque
from typing import Any, Callable, List, Optional

PREFILL = "prefill"
DECODE = "decode"

_DONE = object()          # out-queue sentinel: sequence finished cleanly


OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                     32.0, 48.0, 64.0, 96.0, 128.0)
STEP_SECONDS_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                        0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


def _occupancy_hist():
    from ray_tpu.util import metrics
    return metrics.Histogram(
        "ray_tpu_serve_batch_occupancy",
        "live (non-pad) sequences per continuous-batching step — p50 > 1 "
        "means iteration-level batching is actually coalescing work",
        boundaries=OCCUPANCY_BUCKETS,
        tag_keys=("Deployment", "Phase"))


def _step_hist():
    from ray_tpu.util import metrics
    return metrics.Histogram(
        "ray_tpu_serve_batch_step_seconds",
        "wall time of one continuous-batching step call, split by "
        "scheduled phase (prefill | decode)",
        boundaries=STEP_SECONDS_BUCKETS,
        tag_keys=("Deployment", "Phase"))


class Sequence:
    """One request's slot in the running batch (user-visible in the step
    function). ``state`` is scratch space the step function owns across
    steps (KV cache handle, cursor, ...); the engine never touches it."""

    __slots__ = ("args", "kwargs", "model_id", "state", "phase", "steps",
                 "request_id", "_out", "_done", "_cancelled", "_defers",
                 "_trace", "_parent_span", "_t_submit", "_t_first_step",
                 "_t_phase_start", "_t_last_step")

    def __init__(self, args: tuple, kwargs: dict, model_id: str = ""):
        self.args = args
        self.kwargs = kwargs
        self.model_id = model_id
        self.state: Any = None
        self.phase = PREFILL
        self.steps = 0                       # steps this sequence ran in
        self.request_id = ""
        self._out: asyncio.Queue = asyncio.Queue()
        self._done = False
        self._cancelled = False
        self._defers = 0   # times passed over by model-locality admission
        self._trace = None                   # RequestTrace (sampled) | None
        self._parent_span = None             # replica exec span dict | None
        self._t_submit = time.monotonic()
        self._t_first_step = 0.0
        self._t_phase_start = 0.0
        # When this sequence last participated in a step — the model-
        # fairness clock (_plan runs the most-starved model first).
        self._t_last_step = self._t_submit

    def __repr__(self):
        return (f"Sequence(model={self.model_id!r}, phase={self.phase}, "
                f"steps={self.steps})")


class _SeqError:
    __slots__ = ("err",)

    def __init__(self, err: BaseException):
        self.err = err


class BatchScheduler:
    """Per-replica iteration-level batch scheduler: one step loop, a
    fixed slot array (the pad bucket), an admission queue, and
    per-sequence output queues. All state lives on ONE event loop (the
    replica's); no locks needed."""

    def __init__(self, step_fn: Callable, *, max_batch_size: int = 8,
                 prefill_chunk: Optional[int] = None,
                 decode_starvation_steps: int = 4,
                 deployment: str = ""):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._step_fn = step_fn
        self._max = int(max_batch_size)
        self._prefill_chunk = int(prefill_chunk or max_batch_size)
        self._starve_bound = max(1, int(decode_starvation_steps))
        self._deployment = deployment
        self._slots: List[Optional[Sequence]] = [None] * self._max
        self._waiting: deque = deque()
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._prefill_streak = 0      # consecutive prefill steps w/ decode
        self._cancel_pending = 0      # cancels since the last reap pass
        # Stats (tests + bench introspection; metrics export the same).
        self.steps_total = 0
        self.steps_prefill = 0
        self.steps_decode = 0
        self.occupancy_sum = 0
        self.admitted_total = 0
        self.retired_total = 0
        # Exact per-step samples for stats(): occupancy is small-integer
        # valued (counter is exact + O(max_batch_size) memory); step
        # times keep a bounded window per phase.
        self._occ_counts: dict = {}
        self._step_times = {PREFILL: deque(maxlen=4096),
                            DECODE: deque(maxlen=4096)}
        self._occ_slot = None
        self._step_slots: dict = {}
        self._metrics_gen = -1

    # ------------------------------------------------------------------
    # Submission (called from request handlers on the replica loop)
    # ------------------------------------------------------------------
    async def stream(self, args: tuple, kwargs: dict, model_id: str = ""):
        """Submit one request; yield its emissions as the batch produces
        them. Closing the generator (client gone, deadline cancel)
        retires the sequence at the next step boundary — leave is as
        boundary-aligned as join."""
        seq = Sequence(args, kwargs, model_id)
        self._attach_trace(seq)
        self._ensure_loop()
        self._waiting.append(seq)
        self._wake.set()
        try:
            while True:
                item = await seq._out.get()
                if item is _DONE:
                    return
                if isinstance(item, _SeqError):
                    raise item.err
                yield item
        finally:
            # Consumer went away (completed, cancelled, or errored):
            # the step loop frees the slot at the next boundary.
            seq._cancelled = True
            if not seq._done:
                self._cancel_pending += 1
            self._wake.set()

    def _attach_trace(self, seq: Sequence) -> None:
        """Capture the request's trace context + the replica exec span
        so the step loop (a DIFFERENT task, no request contextvars) can
        stamp phases and parent prefill/decode spans correctly."""
        try:
            from ray_tpu.serve import request_trace
            ctx = request_trace.current()
            if ctx is not None and ctx.sampled:
                seq._trace = ctx
                seq.request_id = ctx.request_id
            from ray_tpu.util import tracing
            seq._parent_span = tracing.active_span()
        except Exception:  # noqa: BLE001 — tracing must not fail requests
            pass

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._run())

    # ------------------------------------------------------------------
    # Step loop
    # ------------------------------------------------------------------
    def _live(self) -> List[Sequence]:
        return [s for s in self._slots if s is not None]

    def _retire_cancelled(self) -> None:
        if not self._cancel_pending:
            return   # hot path: no scan when nothing cancelled
        self._cancel_pending = 0
        for i, seq in enumerate(self._slots):
            if seq is not None and seq._cancelled and not seq._done:
                self._finish(seq, i)
        # Never-joined cancels (client gave up while the batch was
        # saturated) must be reaped from the WAITING queue too — under
        # sustained retry load with no slot turnover they would pile up
        # unboundedly, each pinning its prompt payload.
        if self._waiting:
            self._waiting = deque(s for s in self._waiting
                                  if not s._cancelled)

    # After this many model-locality pass-overs a waiting request is
    # admitted strictly FIFO: locality is a preference, starvation is
    # not (the admission analogue of decode_starvation_steps).
    ADMIT_STARVATION_DEFERS = 8

    def _admit(self) -> None:
        """Join-at-step-boundary: fill free slots from the waiting queue.
        Same-model grouping applies here too — prefer requests matching
        the model already dominant in the live batch, so a freed slot
        backfills without forcing a model swap mid-batch. A request
        passed over ADMIT_STARVATION_DEFERS times is admitted FIFO
        regardless, so sustained same-model load can never starve a
        different model's waiter while slots keep turning over."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free or not self._waiting:
            return
        live = self._live()
        resident = {s.model_id for s in live}
        # Two passes: matching-model first (stable FIFO within each).
        for pass_match in (True, False):
            if not free:
                break
            kept: deque = deque()
            while self._waiting and free:
                seq = self._waiting.popleft()
                if seq._cancelled:
                    continue   # gave up before ever joining
                match = ((not resident) or (seq.model_id in resident)
                         or seq._defers >= self.ADMIT_STARVATION_DEFERS)
                if pass_match and not match:
                    seq._defers += 1
                    kept.append(seq)
                    continue
                i = free.pop(0)
                self._slots[i] = seq
                resident.add(seq.model_id)
                self.admitted_total += 1
            kept.extend(self._waiting)
            self._waiting = kept

    @staticmethod
    def _starved_model(cands) -> str:
        """Model of the sequence that has gone longest without a step —
        model-level fairness: after model A runs, its sequences' clocks
        advance past model B's, so co-resident models alternate instead
        of the lowest-slot model monopolizing every step."""
        return min(cands, key=lambda it: it[1]._t_last_step)[1].model_id

    def _plan(self):
        """(phase, model_id, [slot indices]) for the next step, or None
        when no live sequence is runnable. Prefill priority bounded by
        the decode-starvation rule; one model id per step, most-starved
        model first."""
        prefill = [(i, s) for i, s in enumerate(self._slots)
                   if s is not None and s.phase == PREFILL]
        decode = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None and s.phase == DECODE]
        run_prefill = bool(prefill) and (
            not decode or self._prefill_streak < self._starve_bound)
        if run_prefill:
            model = self._starved_model(prefill)
            idx = [i for i, s in prefill
                   if s.model_id == model][: self._prefill_chunk]
            self._prefill_streak += 1 if decode else 0
            return PREFILL, model, idx
        if decode:
            model = self._starved_model(decode)
            idx = [i for i, s in decode if s.model_id == model]
            self._prefill_streak = 0
            return DECODE, model, idx
        return None

    def _padded(self, idx: List[int]) -> List[Optional[Sequence]]:
        """The step function's view: ALWAYS max_batch_size slots, live
        sequences in their slot positions, None pads elsewhere — the
        constant shape a jitted step traces once."""
        batch: List[Optional[Sequence]] = [None] * self._max
        for i in idx:
            batch[i] = self._slots[i]
        return batch

    async def _run(self) -> None:
        while True:
            self._retire_cancelled()
            self._admit()
            plan = self._plan()
            if plan is None:
                if not self._waiting:
                    self._wake.clear()
                    await self._wake.wait()
                continue
            phase, _model, idx = plan
            batch = self._padded(idx)
            for i in idx:
                seq = self._slots[i]
                if seq._t_first_step == 0.0:
                    seq._t_first_step = time.monotonic()
                    seq._t_phase_start = time.time()
            t0 = time.perf_counter()
            try:
                results = self._step_fn(phase, batch)
                if asyncio.iscoroutine(results):
                    results = await results
            except Exception as e:  # noqa: BLE001 — fail THIS step's seqs
                for i in idx:
                    seq = self._slots[i]
                    if seq is not None:
                        seq._out.put_nowait(_SeqError(e))
                        self._finish(seq, i, error=True)
                await asyncio.sleep(0)
                continue
            dt = time.perf_counter() - t0
            occ = len(idx)
            # ALL step accounting lives here — a step that ran is a step
            # that counts, even if _apply rejects its results, so
            # stats() means/percentiles and the exported histograms
            # always describe the same step set.
            self.steps_total += 1
            if phase == PREFILL:
                self.steps_prefill += 1
            else:
                self.steps_decode += 1
            self.occupancy_sum += occ
            self._occ_counts[occ] = self._occ_counts.get(occ, 0) + 1
            self._step_times[phase].append(dt)
            self._observe_step(phase, occ, dt)
            try:
                self._apply(phase, idx, results)
            except Exception as e:  # noqa: BLE001 — loop must survive
                # Belt-and-braces: _apply guards malformed results per
                # slot, but ANY escape here would kill the loop task and
                # hang every consumer — fail this step's sequences.
                for i in idx:
                    seq = self._slots[i]
                    if seq is not None:
                        seq._out.put_nowait(_SeqError(e))
                        self._finish(seq, i, error=True)
            # One cooperative yield per step: emissions flush to their
            # consumers and cancellations/admissions land at the
            # boundary, without an idle sleep throttling throughput.
            await asyncio.sleep(0)

    def _apply(self, phase: str, idx: List[int], results) -> None:
        if results is None or len(results) != self._max:
            err = ValueError(
                f"continuous-batching step must return exactly "
                f"{self._max} slots (got "
                f"{'None' if results is None else len(results)}) — the "
                f"pad bucket is part of the contract")
            for i in idx:
                seq = self._slots[i]
                if seq is not None:
                    seq._out.put_nowait(_SeqError(err))
                    self._finish(seq, i, error=True)
            return
        now = time.monotonic()
        for i in idx:
            seq = self._slots[i]
            if seq is None:
                continue
            seq.steps += 1
            seq._t_last_step = now    # model-fairness clock
            res = results[i]
            if res is None:
                emission, done = None, False
            elif isinstance(res, (tuple, list)) and len(res) == 2:
                emission, done = res
            else:
                # Malformed per-slot result: fail THIS sequence typed —
                # an unpack error here would kill the step loop and
                # silently hang every other in-flight request.
                seq._out.put_nowait(_SeqError(ValueError(
                    f"continuous-batching step returned {res!r} for a "
                    f"live slot; expected None or (emission, done)")))
                self._finish(seq, i, error=True)
                continue
            if emission is not None and not seq._cancelled:
                seq._out.put_nowait(emission)
            if phase == PREFILL and not done:
                self._to_decode(seq)
            if done:
                self._finish(seq, i)

    def _to_decode(self, seq: Sequence) -> None:
        seq.phase = DECODE
        now = time.time()
        if seq._trace is not None:
            try:
                from ray_tpu._private.flightrec import RQ_PREFILL_END
                if seq._trace.phases[RQ_PREFILL_END] is None:
                    seq._trace.stamp(RQ_PREFILL_END, now)
            except Exception:  # noqa: BLE001
                pass
        self._export_phase_span(seq, PREFILL, now)
        seq._t_phase_start = now

    def _finish(self, seq: Sequence, slot: int, error: bool = False) -> None:
        self._slots[slot] = None
        if seq._done:
            return
        seq._done = True
        self.retired_total += 1
        if not error:
            self._export_phase_span(seq, seq.phase, time.time())
        seq._out.put_nowait(_DONE)

    def _export_phase_span(self, seq: Sequence, phase: str,
                           end: float) -> None:
        """One prefill/decode span per sequence, parented under the
        replica's exec span so `ray_tpu timeline --request` shows the
        phase split inside the handler slice."""
        if seq._trace is None or not seq._t_phase_start:
            return
        try:
            from ray_tpu.util import tracing
            parent = seq._parent_span
            tracing.export_span({
                "kind": "span", "trace_id": seq._trace.trace_id,
                "span_id": os.urandom(8).hex(),
                "parent_id": parent["span_id"] if parent
                else seq._trace.parent_span_id,
                "name": f"cb:{phase}", "task_id": seq.request_id,
                "start": seq._t_phase_start, "end": end,
                "pid": os.getpid(), "steps": seq.steps,
            })
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # Metrics + introspection
    # ------------------------------------------------------------------
    def _observe_step(self, phase: str, occupancy: int, dt: float) -> None:
        try:
            from ray_tpu.util import metrics as _m
            if self._metrics_gen != _m._generation:
                self._metrics_gen = _m._generation
                self._occ_slot = None
                self._step_slots.clear()
            if self._occ_slot is None:
                self._occ_slot = {}
                hist = _occupancy_hist()
                step = _step_hist()
                for ph in (PREFILL, DECODE):
                    self._occ_slot[ph] = hist._slot(
                        {"Deployment": self._deployment, "Phase": ph})
                    self._step_slots[ph] = step._slot(
                        {"Deployment": self._deployment, "Phase": ph})
            _m.observe_into(self._occ_slot[phase], float(occupancy))
            _m.observe_into(self._step_slots[phase], dt)
        except Exception:  # noqa: BLE001 — metrics must not fail steps
            pass

    def _occ_percentile(self, q: float) -> float:
        total = sum(self._occ_counts.values())
        if not total:
            return 0.0
        rank = q * (total - 1)
        seen = 0
        for occ in sorted(self._occ_counts):
            seen += self._occ_counts[occ]
            if seen > rank:
                return float(occ)
        return float(max(self._occ_counts))

    @staticmethod
    def _time_percentile(samples, q: float) -> float:
        if not samples:
            return 0.0
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * len(s)))]

    def stats(self) -> dict:
        live = len(self._live())
        return {
            "steps_total": self.steps_total,
            "steps_prefill": self.steps_prefill,
            "steps_decode": self.steps_decode,
            "occupancy_mean": (self.occupancy_sum / self.steps_total
                               if self.steps_total else 0.0),
            "occupancy_p50": self._occ_percentile(0.50),
            "occupancy_p95": self._occ_percentile(0.95),
            "step_ms": {
                ph: {
                    "n": len(ts),
                    "p50": round(
                        self._time_percentile(ts, 0.50) * 1e3, 3),
                    "p95": round(
                        self._time_percentile(ts, 0.95) * 1e3, 3),
                } for ph, ts in self._step_times.items()},
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
            "live": live,
            "waiting": len(self._waiting),
        }


def continuous_batching(_fn=None, *, max_batch_size: int = 8,
                        prefill_chunk: Optional[int] = None,
                        decode_starvation_steps: int = 4):
    """Decorator: the decorated method IS the step function
    ``step(self, phase, batch)``; CALLING it with one request's args
    submits that request to the per-instance BatchScheduler and returns
    an async generator of the request's emissions (mirrors the
    @serve.batch dual-signature convention)."""

    def wrap(fn):
        attr = f"__serve_cb_scheduler_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            # Method vs plain function: descriptor check, exactly like
            # @serve.batch — args[0] owns the scheduler when the wrapper
            # is a class attribute of its type.
            is_method = bool(args) and getattr(
                type(args[0]), fn.__name__, None) is wrapper
            if is_method:
                owner = args[0]
                call_args = args[1:]
                sched = getattr(owner, attr, None)
                if sched is None:
                    dep = ""
                    try:
                        from ray_tpu.serve.replica import get_request_context
                        rc = get_request_context()
                        dep = getattr(rc, "deployment", "") or ""
                    except Exception:  # noqa: BLE001
                        pass
                    sched = BatchScheduler(
                        lambda phase, batch: fn(owner, phase, batch),
                        max_batch_size=max_batch_size,
                        prefill_chunk=prefill_chunk,
                        decode_starvation_steps=decode_starvation_steps,
                        deployment=dep or type(owner).__name__)
                    setattr(owner, attr, sched)
            else:
                call_args = args
                sched = getattr(wrapper, "_scheduler", None)
                if sched is None:
                    sched = BatchScheduler(
                        fn, max_batch_size=max_batch_size,
                        prefill_chunk=prefill_chunk,
                        decode_starvation_steps=decode_starvation_steps,
                        deployment=fn.__name__)
                    wrapper._scheduler = sched
            from ray_tpu.serve.multiplex import get_multiplexed_model_id
            async for item in sched.stream(call_args, kwargs,
                                           get_multiplexed_model_id()):
                yield item

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
