"""Request-scoped serve tracing: one trace per request, proxy to TPU task.

A request entering any serve ingress (HTTP proxy, binary-RPC proxy,
websocket upgrade) — or created directly on a DeploymentHandle — mints a
`RequestTrace`: a request id, a trace id, and a root span. The context
rides every hop:

  proxy --(contextvar)--> DeploymentHandle._PendingRequest
        --(wire tuple on handle_request)--> replica
        --(util.tracing contextvar / TaskSpec.trace_ctx)--> any tasks or
          nested handle calls the handler spawns.

Each hop stamps the request phases it owns (flightrec.REQ_PHASE_ORDER)
into a fixed-index record and ships ONE `kind:"serve_request"` event
through this module's EventRing (the PR 5 ring — fixed slots, O(1)
drop-oldest) to the GCS task-event buffer, where `flightrec.build_trace`
renders the whole request as a single chrome trace crossing proxy,
replica, and spawned-task pids, and `latency_summary` folds it into the
/api/latency + `ray_tpu summary` tables. A replayed request (PR 6
queue-preserving failover) stays ONE trace: the handle records an
explicit `replay` hop + span, and the replica's result-cache dedupe
keeps exec spans exactly-once.

Sampling: `RAY_TPU_SERVE_TRACE_SAMPLE` = N records 1 in N requests
(default 1 = every request; 0 disables recording entirely). The sampled
bit is decided ONCE at mint time and travels with the context, so a
request is either fully traced on every hop or not at all — never a
torn trace. Replica-side SLO counters (serve/slo.py inputs) are NOT
sampled; they count every request regardless.
"""

from __future__ import annotations

import asyncio
import contextvars
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.flightrec import (  # noqa: F401 — re-exported
    EventRing, REQ_PHASE_ORDER, REQ_RECORD_LEN, RQ_ADMISSION, RQ_DISPATCH,
    RQ_EXEC_END, RQ_EXEC_START, RQ_FIRST_ITEM, RQ_PREFILL_END,
    RQ_PROXY_RECV, RQ_QUEUE_WAIT, RQ_REPLY, request_phase_durations)

_SAMPLE_ENV = "RAY_TPU_SERVE_TRACE_SAMPLE"

_current: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_trace", default=None)

_sample_n = None        # resolved lazily from the env (tests override)
_sample_counter = 0
_sample_lock = threading.Lock()


def sample_n() -> int:
    global _sample_n
    if _sample_n is None:
        try:
            _sample_n = max(0, int(os.environ.get(_SAMPLE_ENV, "1")))
        except ValueError:
            _sample_n = 1
    return _sample_n


def set_sample_n(n: Optional[int]) -> None:
    """Override the sampling rate for this process (None = re-read the
    env). 0 disables request tracing; N records 1 in N requests."""
    global _sample_n
    _sample_n = None if n is None else max(0, int(n))


def _sampled() -> bool:
    """One coin flip per minted request: strict round-robin 1-in-N."""
    n = sample_n()
    if n <= 0:
        return False
    if n == 1:
        return True
    global _sample_counter
    with _sample_lock:
        _sample_counter += 1
        return _sample_counter % n == 1


class RequestTrace:
    """Per-request trace context: ids + the hop-local phase record.

    `request_id` is unique per request; `trace_id` is the ROOT request's
    id (nested handle calls inside a handler inherit it), which is what
    groups every hop, replay, and spawned-task span into one trace."""

    __slots__ = ("request_id", "trace_id", "parent_span_id", "sampled",
                 "deployment", "phases", "replays", "root_span", "owned",
                 "replica_hop", "error", "_done")

    def __init__(self, request_id: str, trace_id: str,
                 parent_span_id: str = "", sampled: bool = True,
                 deployment: str = ""):
        self.request_id = request_id
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.sampled = sampled
        self.deployment = deployment
        self.phases = [None] * REQ_RECORD_LEN
        self.replays = 0
        self.root_span: Optional[dict] = None
        # True on the hop that minted this context — that hop records the
        # trace's root event/span at finish(); non-minting hops must not.
        self.owned = False
        # True while bound as the REPLICA hop's context (the replica
        # binds it so span()/the batch scheduler can find the trace).
        # A nested handle call inside the handler must NOT adopt this
        # record as its own (it would stamp dispatch into the replica's
        # phase record); it minted a child via exec-span adoption before
        # the replica bound anything, and still does.
        self.replica_hop = False
        # Exception class name when the request failed on this hop; rides
        # the hop event's spare slot into the GCS buffer, where the trace
        # search's --errors-only filter keys on it.
        self.error = ""
        self._done = False

    # -- phase stamps ---------------------------------------------------
    def stamp(self, idx: int, t: Optional[float] = None) -> float:
        t = time.time() if t is None else t
        self.phases[idx] = t
        return t

    # -- wire form (handle -> replica) ----------------------------------
    def wire(self) -> Tuple[str, str, str, bool]:
        return (self.request_id, self.trace_id, self.parent_span_id,
                self.sampled)

    @classmethod
    def from_wire(cls, w, deployment: str = "") -> "RequestTrace":
        request_id, trace_id, parent, sampled = w
        return cls(request_id, trace_id, parent, sampled, deployment)

    # -- replay marker --------------------------------------------------
    def record_replay(self, reason: str = "") -> None:
        """One failover re-dispatch: keeps the request a single trace
        with an explicit `replay` hop (event + span)."""
        self.replays += 1
        if not self.sampled:
            return
        now = time.time()
        record_event(self, "replay", phases=None, t=now)
        from ray_tpu.util import tracing
        tracing.export_span({
            "kind": "span", "trace_id": self.trace_id,
            "span_id": os.urandom(8).hex(),
            "parent_id": self.parent_span_id,
            "name": "replay", "task_id": self.request_id,
            "start": now, "end": now, "pid": os.getpid(),
            "reason": reason[:200],
        })


def mint(deployment: str, request_id: str = "",
         hop: str = "proxy") -> RequestTrace:
    """New trace context at an entry point. Inside an already-traced
    handler (nested handle call) the child inherits the ACTIVE trace —
    one request stays one tree across deployment graphs.

    `request_id` may be client-supplied (X-Request-Id) — it names the
    trace only; replay dedupe uses a private id (handle.py)."""
    from ray_tpu.util import tracing
    rid = (request_id or "")[:64] or os.urandom(8).hex()
    # Adopt ONLY a serve exec span (a replica handler making a nested
    # handle call) — identified by the marker start_exec_span sets.
    # Neither tracing.current_context() (fabricates a fresh random trace
    # whenever tracing.enable() is on) nor a bare active span (task
    # spans LEAK into the proxy's connection-handler context through
    # asyncio.start_server when a traced control task started the
    # server) is safe to adopt: both sever the request id from the span
    # tree.
    span = tracing.active_span()
    if span is not None and span.get("serve_exec"):
        ctx = RequestTrace(rid, span["trace_id"], span["span_id"],
                           sampled=sample_n() > 0, deployment=deployment)
        ctx.owned = True
        return ctx
    ctx = RequestTrace(rid, rid, "", sampled=_sampled(),
                       deployment=deployment)
    ctx.owned = True
    if ctx.sampled:
        ctx.root_span = {
            "kind": "span", "trace_id": ctx.trace_id,
            "span_id": os.urandom(8).hex(), "parent_id": "",
            "name": f"request:{deployment}" if deployment else "request",
            "task_id": rid, "start": time.time(), "end": None,
            "pid": os.getpid(), "hop": hop,
        }
        ctx.parent_span_id = ctx.root_span["span_id"]
    return ctx


def finish(ctx: Optional[RequestTrace], hop: str) -> None:
    """Close out the minting hop: stamp `reply` if the hop hasn't,
    record the hop event, and export the root span. Idempotent — replay
    loops and settle callbacks may race to call it."""
    if ctx is None or not ctx.sampled or ctx._done:
        return
    ctx._done = True
    if ctx.phases[RQ_REPLY] is None:
        ctx.stamp(RQ_REPLY)
    record_event(ctx, hop, phases=list(ctx.phases))
    if ctx.root_span is not None:
        from ray_tpu.util import tracing
        span, ctx.root_span = ctx.root_span, None
        tracing.export_span(span)


# -- contextvar plumbing (proxy -> handle, same process) ---------------

def bind(ctx: Optional[RequestTrace]):
    return _current.set(ctx)


def unbind(token) -> None:
    _current.reset(token)


def current() -> Optional[RequestTrace]:
    return _current.get()


# -- user-facing span API ----------------------------------------------

class _NullSpan:
    """No-op context manager: span() outside a traced request (or on an
    unsampled one) costs nothing and never fails the handler."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _UserSpan:
    __slots__ = ("_ctx", "_name", "_span")

    def __init__(self, ctx: "RequestTrace", name: str):
        self._ctx = ctx
        self._name = str(name)[:120]
        self._span = None

    def __enter__(self):
        from ray_tpu.util import tracing
        parent = tracing.active_span()
        trace_ctx = ((parent["trace_id"], parent["span_id"])
                     if parent is not None
                     else (self._ctx.trace_id, self._ctx.parent_span_id))
        self._span = tracing.start_span(
            self._name, trace_ctx, self._ctx.request_id)
        return self

    def __exit__(self, *exc):
        from ray_tpu.util import tracing
        try:
            tracing.export_span(tracing.end_span(self._span))
        except Exception:  # noqa: BLE001 — tracing never fails handlers
            pass
        return False


def span(name: str):
    """User context manager: mark a sub-phase inside a serve handler.

        from ray_tpu.serve import request_trace
        with request_trace.span("tokenize"):
            ids = tok(prompt)

    The span nests under the replica's exec span (or whatever span is
    active in the handler's context — spans nest arbitrarily deep), is
    stamped with the request id, and renders inside the handler slice in
    ``ray_tpu timeline --request <id>``. On an unsampled or untraced
    request this is a no-op."""
    ctx = current()
    if ctx is None or not ctx.sampled:
        return _NULL_SPAN
    return _UserSpan(ctx, name)


# -- replica-side span helpers -----------------------------------------

def start_exec_span(ctx: RequestTrace, name: str) -> Optional[dict]:
    """Open the replica exec span AND make it the active tracing span,
    so tasks / nested handle calls the handler spawns parent under it
    (TaskSpec.trace_ctx rides the existing contextvar machinery)."""
    if not ctx.sampled:
        return None
    from ray_tpu.util import tracing
    span = tracing.start_span(name, (ctx.trace_id, ctx.parent_span_id),
                              ctx.request_id)
    span["serve_exec"] = True  # mint() adopts ONLY these (nested calls)
    return span


def finish_exec_span(span: Optional[dict]) -> None:
    if span is None:
        return
    from ray_tpu.util import tracing
    tracing.export_span(tracing.end_span(span))


# -- event ring + flush -------------------------------------------------

_ring = EventRing(8192)
_flush_lock = threading.Lock()
_flush_core = None          # core whose loop runs the current flusher


def record_event(ctx: RequestTrace, hop: str,
                 phases: Optional[list] = None,
                 t: Optional[float] = None) -> None:
    """One hop's request event into the ring (skipped unsampled), plus
    the per-deployment phase histograms."""
    if not ctx.sampled:
        return
    if phases is not None:
        _observe_phases(ctx.deployment, phases)
    _ring.record(ctx.request_id, ctx.trace_id, ctx.deployment, hop,
                 tuple(phases) if phases is not None else None,
                 ctx.replays, time.time() if t is None else t,
                 ctx.error or None)
    _ensure_flusher()


def _fold(rec) -> dict:
    rid, trace_id, deployment, hop, phases, replays, t, error = rec
    out = {
        "kind": "serve_request", "request_id": rid, "trace_id": trace_id,
        "deployment": deployment, "hop": hop, "time": t,
        "pid": os.getpid(),
    }
    if phases is not None:
        out["phases"] = list(phases)
    if replays:
        out["replays"] = replays
    if error:
        out["error"] = error
    return out


def _ensure_flusher() -> None:
    """Start (or restart after shutdown) the flush loop on the core
    worker's event loop. Records made before any core exists just wait
    in the ring — capacity-bounded, drop-oldest."""
    global _flush_core
    from ray_tpu._private import worker_api
    core = worker_api.peek_core()
    if core is None or getattr(core, "_shutdown", False):
        return
    if _flush_core is core:  # hot path: flusher already running
        return
    with _flush_lock:
        if _flush_core is core:
            return
        _flush_core = core
    try:
        core.loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(_flush_loop(core)))
    except RuntimeError:
        with _flush_lock:
            _flush_core = None


async def _flush_loop(core) -> None:
    global _flush_core
    try:
        while not getattr(core, "_shutdown", False):
            await asyncio.sleep(0.5)
            await flush_now(core)
    finally:
        with _flush_lock:
            if _flush_core is core:
                _flush_core = None


async def flush_now(core) -> int:
    """Drain the ring to the GCS task-event buffer; returns rows sent."""
    if core.gcs is None or core.gcs.closed:
        return 0
    buf = _ring.drain()
    if not buf:
        return 0
    events = [_fold(r) for r in buf]
    try:
        await core.gcs.request("report_task_events", {"events": events})
    except Exception:  # noqa: BLE001 — ring re-fills; next tick retries
        return 0
    return len(events)


# -- per-deployment phase histograms ------------------------------------

REQ_PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_hist_slots: Dict[Tuple[str, str], Any] = {}
_hist_gen = -1


def _observe_phases(deployment: str, phases) -> None:
    """Fold one hop's stamps into ray_tpu_serve_request_phase_seconds
    (Deployment x Phase), slot-cached like the task-phase fold."""
    global _hist_gen
    from ray_tpu.util import metrics as _m
    if _hist_gen != _m._generation:
        _hist_gen = _m._generation
        _hist_slots.clear()
    hist = None
    for phase, d in request_phase_durations(phases):
        slot = _hist_slots.get((deployment, phase))
        if slot is None:
            if hist is None:
                hist = _m.Histogram(
                    "ray_tpu_serve_request_phase_seconds",
                    "serve request phase latency (request flight "
                    "recorder): proxy_recv/admission/queue_wait/"
                    "dispatch/exec/first_item/reply gaps per hop",
                    boundaries=REQ_PHASE_BUCKETS,
                    tag_keys=("Deployment", "Phase"))
            slot = hist._slot({"Deployment": deployment, "Phase": phase})
            _hist_slots[(deployment, phase)] = slot
        _m.observe_into(slot, d)
