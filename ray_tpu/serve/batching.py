"""@serve.batch: dynamic request batching (queue-then-flush).

Reference parity: python/ray/serve/batching.py. Calls to the decorated
async function are queued; a flusher invokes the underlying function with a
list of requests once max_batch_size accumulate or batch_wait_timeout_s
elapses. On TPU this is the lever that keeps the jitted callable fed with a
fixed batch dimension: with ``pad_batches=True`` every flush ships EXACTLY
max_batch_size entries (short batches padded with ``pad_value``), so the
jitted function traces one shape and never recompiles.

For iteration-level batching — requests joining/leaving a RUNNING batch
at step boundaries (token generation) — use
``@serve.continuous_batching`` (serve/continuous_batching.py) instead;
this decorator is the right shape for one-shot batch inference
(embed/classify/score) where the whole batch finishes together.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float, pad_batches: bool = False,
                 pad_value: Any = None):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._pad = pad_batches
        self._pad_value = pad_value
        self._queue: List = []   # (args_tuple, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, args: tuple):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._queue.append((args, fut))
        if len(self._queue) >= self._max:
            self._flush_now()
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._delayed_flush())
        return await fut

    async def _delayed_flush(self):
        await asyncio.sleep(self._timeout)
        self._flush_now()

    def _flush_now(self):
        if not self._queue:
            return
        batch, self._queue = self._queue, []
        asyncio.ensure_future(self._run_batch(batch))

    async def _run_batch(self, batch):
        args_lists = None
        futures = [f for _a, f in batch]
        try:
            # Transpose: fn(self?, [x0, x1...], [y0, y1...])
            n_args = len(batch[0][0])
            args_lists = tuple([a[i] for a, _f in batch]
                               for i in range(n_args))
            if self._pad and len(batch) < self._max:
                # Fixed bucket: every flush is exactly max_batch_size
                # long, so a jitted fn traces ONE shape. Pad results are
                # dropped below (zip stops at the real futures).
                fill = self._max - len(batch)
                args_lists = tuple(lst + [self._pad_value] * fill
                                   for lst in args_lists)
            results = self._fn(*args_lists)
            if asyncio.iscoroutine(results):
                results = await results
            expect = self._max if self._pad else len(batch)
            if len(results) != expect:
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {expect}")
            for f, r in zip(futures, results):
                if not f.done():
                    f.set_result(r)
        except Exception as e:
            for f in futures:
                if not f.done():
                    f.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01, pad_batches: bool = False,
          pad_value: Any = None):
    """Decorator: async fn(self, item) -> result, executed as fn(self,
    [items]) -> [results]. ``pad_batches`` pads every flush to
    max_batch_size with ``pad_value`` (constant shapes for jit); the fn
    must then return max_batch_size results, pad outputs are dropped."""

    def wrap(fn):
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            # Methods: the wrapper is a class attribute of args[0]'s type
            # (descriptor check — NOT duck-typing on args[0], which would
            # misroute plain functions whose first argument happens to be
            # an object). Each instance gets its own queue.
            is_method = bool(args) and getattr(
                type(args[0]), fn.__name__, None) is wrapper
            if is_method:
                owner = args[0]
                bound_args = args[1:]
                q = getattr(owner, attr, None)
                if q is None:
                    q = _BatchQueue(
                        lambda *ls: fn(owner, *ls),
                        max_batch_size, batch_wait_timeout_s,
                        pad_batches, pad_value)
                    setattr(owner, attr, q)
            else:
                bound_args = args
                q = getattr(wrapper, "_queue", None)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s,
                                    pad_batches, pad_value)
                    wrapper._queue = q
            return await q.submit(bound_args)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
