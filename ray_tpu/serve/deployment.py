"""Deployment + Application graph nodes.

Reference parity: python/ray/serve/deployment.py (Deployment, .options,
.bind producing an Application). A bound Application may have other
Applications among its init args — they resolve to DeploymentHandles at
replica construction time (deployment graph composition).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Optional, Tuple, Union

from ray_tpu.serve.config import (AutoscalingConfig, DeploymentConfig,
                                  SLOConfig)


@dataclass
class Deployment:
    func_or_class: Union[Callable, type]
    name: str
    version: str = "1"
    config: DeploymentConfig = field(default_factory=DeploymentConfig)
    route_prefix: Optional[str] = None

    def options(self, *, name: Optional[str] = None,
                version: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                user_config: Any = None,
                ray_actor_options: Optional[dict] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                route_prefix: Optional[str] = None,
                max_queued_requests: Optional[int] = None,
                request_replay: Optional[bool] = None,
                request_timeout_s: Optional[float] = None,
                slice_spread: Optional[bool] = None,
                slo_config: Optional[SLOConfig] = None) -> "Deployment":
        cfg = replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if user_config is not None:
            cfg.user_config = user_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if autoscaling_config is not None:
            cfg.autoscaling_config = autoscaling_config
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if request_replay is not None:
            cfg.request_replay = request_replay
        if request_timeout_s is not None:
            cfg.request_timeout_s = request_timeout_s
        if slice_spread is not None:
            cfg.slice_spread = slice_spread
        if slo_config is not None:
            cfg.slo_config = slo_config
        return Deployment(
            func_or_class=self.func_or_class,
            name=name or self.name,
            version=version or self.version,
            config=cfg,
            route_prefix=(route_prefix if route_prefix is not None
                          else self.route_prefix),
        )

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "Deployments are not directly callable; use .bind() + serve.run "
            "and call the returned handle.")


@dataclass
class Application:
    deployment: Deployment
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)

    def flatten(self) -> Dict[str, "Application"]:
        """All applications in this graph keyed by deployment name."""
        out: Dict[str, Application] = {}

        def visit(app: Application):
            out[app.deployment.name] = app
            for a in list(app.init_args) + list(app.init_kwargs.values()):
                if isinstance(a, Application):
                    visit(a)
        visit(self)
        return out


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               version: str = "1", num_replicas: int = 1,
               max_ongoing_requests: int = 100,
               user_config: Any = None,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               route_prefix: Optional[str] = None,
               max_queued_requests: int = -1,
               request_replay: bool = False,
               request_timeout_s: Optional[float] = None,
               slice_spread: bool = True,
               slo_config: Optional[SLOConfig] = None):
    """@serve.deployment decorator (reference: serve/api.py deployment)."""

    def wrap(f_or_c):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=autoscaling_config,
            max_queued_requests=max_queued_requests,
            request_replay=request_replay,
            request_timeout_s=request_timeout_s,
            slice_spread=slice_spread,
            slo_config=slo_config,
        )
        return Deployment(func_or_class=f_or_c,
                          name=name or f_or_c.__name__,
                          version=version, config=cfg,
                          route_prefix=route_prefix)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
