"""Binary RPC ingress: the gRPC-proxy equivalent on the framed-RPC layer.

Reference parity: python/ray/serve/_private/proxy.py:533 (gRPCProxy) — a
second, non-HTTP ingress sharing the same deployment router, serving unary
and server-streaming calls. The reference speaks protobuf/HTTP2; here the
transport is the framework's own length-prefixed RPC
(ray_tpu/_private/rpc.py), so clients use ServeRpcClient instead of a
generated stub — same capability, no grpc dependency.

Wire methods:
  serve_unary  {app, deployment?, method?, args, kwargs} -> result
  serve_stream {...same..., call_id}
      -> PUSH "serve_stream_item" {call_id, item} per yielded item
      -> response {"items": n} when the stream completes
"""

from __future__ import annotations

import asyncio
import threading
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu._private import rpc


def _reraise_typed(e: "rpc.RemoteRpcError"):
    """Map a remote serve error back to its typed class (the generated-
    stub analogue of gRPC status codes: BackPressureError carries
    RESOURCE_EXHAUSTED, RequestTimeoutError DEADLINE_EXCEEDED,
    ReplicaDiedError UNAVAILABLE). Instances are built through their
    real constructors so every documented field exists and the error
    stays picklable; the remote message replaces the synthesized one."""
    from ray_tpu.serve import exceptions as serr
    factory = {
        "BackPressureError": lambda: serr.BackPressureError("", 0, 0),
        "RequestTimeoutError": lambda: serr.RequestTimeoutError(
            "", 0.0, "remote"),
        "ReplicaDiedError": lambda: serr.ReplicaDiedError(
            "", e.err_message),
        "ReplicaDrainingError": lambda: serr.ReplicaDrainingError(""),
    }.get(e.err_type)
    if factory is None:
        raise e
    err = factory()
    err.args = (e.err_message,)
    raise err from e


class GrpcProxyActor:
    """Ingress actor: RpcServer in front of the deployment router."""

    ROUTE_REFRESH_S = 1.0

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server: Optional[rpc.RpcServer] = None
        self._routes: Dict[str, tuple] = {}
        self._handles: Dict[tuple, Any] = {}
        self._last_refresh = 0.0
        self._num_requests = 0

    async def ready(self) -> int:
        if self._server is None:
            self._server = rpc.RpcServer("serve-grpc-proxy")
            self._server.register("serve_unary", self._rpc_unary)
            self._server.register("serve_stream", self._rpc_stream)
            self._port = await self._server.start(self._host, self._port)
            try:
                from ray_tpu.util import metrics
                metrics.start_loop_lag_probe_once("serve_grpc_proxy")
            except Exception:  # noqa: BLE001 — lag probe is best-effort
                pass
        return self._port

    # Stale-while-revalidate (same contract as the HTTP proxy): a
    # controller outage must not fail or stall ingress — refresh attempts
    # are bounded and failures keep serving the cached table.
    CTRL_TIMEOUT_S = 2.0

    async def _refresh_routes(self):
        import asyncio
        from ray_tpu.serve.api import _get_controller_async
        ctrl = await _get_controller_async()
        self._routes = await asyncio.wait_for(
            ctrl.get_route_table.remote().future(),
            timeout=self.CTRL_TIMEOUT_S)

    async def _handle_for(self, payload) -> Any:
        now = time.monotonic()
        if now - self._last_refresh > self.ROUTE_REFRESH_S:
            self._last_refresh = now
            try:
                await self._refresh_routes()
            except Exception:  # noqa: BLE001 — serve from stale routes
                pass
        app = payload.get("app", "default")
        deployment = payload.get("deployment")

        def _ingress():
            for _prefix, (app_name, ingress) in self._routes.items():
                if app_name == app:
                    return ingress
            return None

        if deployment is None:
            # Route to the app's ingress deployment; a just-deployed app
            # may not be in the cached table yet — force one refresh
            # before failing.
            deployment = _ingress()
            if deployment is None:
                try:
                    await self._refresh_routes()
                    self._last_refresh = time.monotonic()
                except Exception:  # noqa: BLE001
                    pass
                deployment = _ingress()
        if deployment is None:
            raise ValueError(f"no application {app!r}")
        key = (app, deployment, payload.get("method") or "__call__")
        handle = self._handles.get(key)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle
            handle = DeploymentHandle(deployment, app_name=app,
                                      method_name=key[2])
            self._handles[key] = handle
        # Multiplexing: a model-id-tagged call rides mux-aware routing
        # (model-resident replica preferred), same as the HTTP header.
        mux_id = payload.get("multiplexed_model_id") or ""
        if mux_id:
            handle = handle.options(multiplexed_model_id=mux_id)
        return handle

    @rpc.non_idempotent
    async def _rpc_unary(self, conn, payload):
        self._num_requests += 1
        t_recv = time.time()
        handle = await self._handle_for(payload)
        # Same request-trace contract as the HTTP proxy: this ingress
        # mints (or adopts the client's request_id) and the handle/
        # replica/spawned tasks join the trace through the contextvar.
        from ray_tpu.serve import request_trace
        trace = request_trace.mint(handle.deployment_name,
                                   request_id=payload.get("request_id", ""))
        trace.stamp(request_trace.RQ_PROXY_RECV, t_recv)
        token = request_trace.bind(trace)
        try:
            return await handle.remote(*payload.get("args", ()),
                                       **payload.get("kwargs", {}))
        finally:
            request_trace.unbind(token)
            request_trace.finish(trace, "proxy")

    @rpc.non_idempotent
    async def _rpc_stream(self, conn, payload):
        self._num_requests += 1
        t_recv = time.time()
        handle = await self._handle_for(payload)
        call_id = payload["call_id"]
        from ray_tpu.serve import request_trace
        trace = request_trace.mint(handle.deployment_name,
                                   request_id=payload.get("request_id", ""))
        trace.stamp(request_trace.RQ_PROXY_RECV, t_recv)
        token = request_trace.bind(trace)
        try:
            gen = handle.options(stream=True).remote(
                *payload.get("args", ()), **payload.get("kwargs", {}))
            n = 0
            async for item in gen:
                if trace.phases[request_trace.RQ_FIRST_ITEM] is None:
                    trace.stamp(request_trace.RQ_FIRST_ITEM)
                # Items stream as PUSH frames; the final RESPONSE closes
                # the call (reference: gRPC server-streaming).
                await conn.push("serve_stream_item",
                                {"call_id": call_id, "item": item})
                n += 1
            return {"items": n}
        finally:
            request_trace.unbind(token)
            request_trace.finish(trace, "proxy")

    def get_num_requests(self) -> int:
        return self._num_requests


class ServeRpcClient:
    """Client for the binary ingress (the generated-stub equivalent).

    Sync facade over a private loop thread, mirroring the ray_tpu client
    pattern; `call` is unary, `stream` yields items as they arrive.
    """

    def __init__(self, address: str):
        self.address = address
        self._conn: Optional[rpc.Connection] = None
        self._loop = asyncio.new_event_loop()
        self._streams: Dict[str, asyncio.Queue] = {}
        ready = threading.Event()

        def run():
            asyncio.set_event_loop(self._loop)
            ready.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-rpc-client")
        self._thread.start()
        ready.wait(10)

    def _on_push(self, method: str, payload):
        if method == "serve_stream_item":
            q = self._streams.get(payload["call_id"])
            if q is not None:
                q.put_nowait(payload["item"])

    async def _ensure_conn(self) -> rpc.Connection:
        if self._conn is None or self._conn.closed:
            self._conn = await rpc.connect(self.address, self._on_push)
        return self._conn

    def call(self, *args, app: str = "default",
             deployment: Optional[str] = None, method: str = "__call__",
             timeout: float = 60.0, request_id: str = "",
             multiplexed_model_id: str = "", **kwargs):
        async def go():
            conn = await self._ensure_conn()
            return await conn.request(
                "serve_unary",
                {"app": app, "deployment": deployment, "method": method,
                 "args": args, "kwargs": kwargs,
                 "request_id": request_id,
                 "multiplexed_model_id": multiplexed_model_id}, timeout)
        try:
            return asyncio.run_coroutine_threadsafe(
                go(), self._loop).result(timeout + 10)
        except rpc.RemoteRpcError as e:
            _reraise_typed(e)

    def stream(self, *args, app: str = "default",
               deployment: Optional[str] = None, method: str = "__call__",
               idle_timeout: float = 60.0, multiplexed_model_id: str = "",
               **kwargs):
        """Generator over streamed items (blocks between items).

        idle_timeout bounds the wait for EACH item, not the whole stream —
        a healthy long stream (e.g. token generation) never times out as
        long as items keep arriving."""
        call_id = uuid.uuid4().hex
        q: "asyncio.Queue" = asyncio.Queue()
        self._streams[call_id] = q
        _END = object()

        async def go():
            try:
                conn = await self._ensure_conn()
                return await conn.request(
                    "serve_stream",
                    {"app": app, "deployment": deployment, "method": method,
                     "args": args, "kwargs": kwargs, "call_id": call_id,
                     "multiplexed_model_id": multiplexed_model_id},
                    timeout=None)
            finally:
                q.put_nowait(_END)

        fut = asyncio.run_coroutine_threadsafe(go(), self._loop)

        async def _next():
            return await q.get()

        try:
            while True:
                item = asyncio.run_coroutine_threadsafe(
                    _next(), self._loop).result(idle_timeout)
                if item is _END:
                    break
                yield item
            try:
                fut.result(5)  # surface stream errors
            except rpc.RemoteRpcError as e:
                _reraise_typed(e)
        finally:
            self._streams.pop(call_id, None)

    def close(self):
        try:
            if self._conn is not None:
                asyncio.run_coroutine_threadsafe(
                    self._conn.close(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
