"""ServeController: the reconciliation brain.

Reference parity: python/ray/serve/_private/controller.py:91 and
deployment_state.py:1226 (DeploymentState/DeploymentStateManager). One named
actor holds target state per app/deployment, reconciles replicas (create,
remove, rolling-update by version), health-checks them, and applies
queue-depth autoscaling. Routers poll get_routing() with a version counter
(the long-poll analogue).

Replica lifecycle (serve-under-fire):

    STARTING --ready--> RUNNING --drain--> DRAINING --> killed

- STARTING replicas are routable only while NO replica is RUNNING (cold
  start: queueing on a starting replica beats failing), so a rolling
  update never routes onto a not-yet-ready replacement.
- Rolling updates and scale-downs are replace-then-drain: the new
  replica must reach RUNNING before the old one drains; draining stops
  new dispatch, hands queued work back to the router, finishes in-flight
  requests within graceful_shutdown_timeout_s, then the actor dies.
- Node drain notices (PR 1's two-phase drain / slice gang drains) are
  consumed from this process's drain-event log: replicas on a draining
  node drain proactively instead of dying with the host.
- Replicas spread across TPU-slice fault domains (config.slice_spread)
  so one slice preemption never takes the whole deployment.
- Readiness is watched by per-replica background tasks — a hung
  constructor can never stall the reconcile loop — and the reconcile /
  health-check periods are jittered so co-resident controllers and
  probe bursts desynchronize.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"

REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_DRAINING = "DRAINING"


class _ReplicaInfo:
    def __init__(self, handle, version: str):
        self.handle = handle
        self.version = version
        self.replica_id = uuid.uuid4().hex[:12]
        self.started = time.monotonic()
        self.ever_healthy = False
        self.state = REPLICA_STARTING
        self.node_id = None            # resolved once READY
        self.target_slice = ""         # slice domain picked at start
        self.ready_task: Optional[asyncio.Task] = None
        self.drain_task: Optional[asyncio.Task] = None
        # Rolling update: the old replica this one replaces — retired
        # (drained) only once this replica reaches RUNNING.
        self.replaces: Optional["_ReplicaInfo"] = None
        self.being_replaced = False


class _DeploymentState:
    STARTUP_GRACE_S = 60.0

    def __init__(self, app_name: str, name: str, blob: bytes, config,
                 version: str):
        self.app_name = app_name
        self.name = name
        self.blob = blob
        self.config = config
        self.version = version
        self.replicas: List[_ReplicaInfo] = []   # STARTING / RUNNING
        self.draining: List[_ReplicaInfo] = []   # retiring, not routable
        self.target_num = config.num_replicas
        self.list_version = 0              # bumped on any replica-set change
        self.last_scale_change = 0.0
        self.next_health_check = 0.0
        self.slo = None                    # DeploymentSLO when configured
        self.last_slo_scale = 0.0
        # Worker prestart-hint throttle (scale-up warm-up).
        self.last_prestart = 0.0
        self.last_prestart_n = 0
        self._rebuild_slo()

    def _rebuild_slo(self):
        if self.config.slo_config is None:
            self.slo = None
            return
        from ray_tpu.serve.slo import DeploymentSLO
        self.slo = DeploymentSLO(self.name, self.config.slo_config)

    def active(self) -> List[_ReplicaInfo]:
        """Replicas that fill a target slot (replacements don't — they
        take their predecessor's slot at swap time)."""
        return [r for r in self.replicas if r.replaces is None]


class ServeController:
    RECONCILE_PERIOD_S = 0.5

    def __init__(self):
        self._deployments: Dict[tuple, _DeploymentState] = {}
        self._routes: Dict[str, tuple] = {}  # route_prefix -> (app, ingress)
        self._proxy = None
        self._reconcile_task = None
        self._started = False
        self._wake: Optional[asyncio.Event] = None
        # deploy_app's inline reconcile and the background loop interleave
        # (replica starts await the slice-domain lookup): without mutual
        # exclusion both can top up the same deployment and overshoot.
        self._reconcile_lock = asyncio.Lock()
        self._drain_seen = 0               # index into drain_events()
        self._domains: Dict[str, list] = {}
        self._node_slice: Dict[Any, str] = {}
        self._nodes_ts = 0.0

    async def _ensure_loops(self):
        if not self._started:
            self._started = True
            self._wake = asyncio.Event()
            loop = asyncio.get_running_loop()
            wake = self._wake

            def _notice():
                loop.call_soon_threadsafe(wake.set)

            try:
                from ray_tpu._private import worker_api
                worker_api.add_drain_event_listener(_notice)
            except Exception:  # noqa: BLE001 — no core (unit tests)
                pass
            try:
                from ray_tpu.util import metrics
                metrics.start_loop_lag_probe_once("serve_controller")
            except Exception:  # noqa: BLE001 — lag probe is best-effort
                pass
            self._reconcile_task = asyncio.ensure_future(
                self._reconcile_loop())

    # ------------------------------------------------------------------
    # Deployment API
    # ------------------------------------------------------------------
    async def deploy_app(self, app_name: str, deployments: List[dict],
                         route_prefix: Optional[str], ingress: str):
        """deployments: [{name, blob, config, version}]"""
        await self._ensure_loops()
        incoming = set()
        for d in deployments:
            key = (app_name, d["name"])
            incoming.add(key)
            cur = self._deployments.get(key)
            if cur is None:
                self._deployments[key] = _DeploymentState(
                    app_name, d["name"], d["blob"], d["config"], d["version"])
            else:
                cur.blob = d["blob"]
                cur.config = d["config"]
                cur.version = d["version"]
                cur.target_num = d["config"].num_replicas
                cur._rebuild_slo()  # fresh windows for the new objective
        # Remove deployments no longer in the app.
        for key in [k for k in self._deployments
                    if k[0] == app_name and k not in incoming]:
            await self._remove_deployment(key)
        if route_prefix is not None:
            self._routes[route_prefix] = (app_name, ingress)
        await self._reconcile_once()
        return True

    async def delete_app(self, app_name: str):
        for key in [k for k in self._deployments if k[0] == app_name]:
            await self._remove_deployment(key)
        self._routes = {r: v for r, v in self._routes.items()
                        if v[0] != app_name}
        return True

    async def _remove_deployment(self, key):
        st = self._deployments.pop(key, None)
        if st is None:
            return
        for r in list(st.replicas):
            if r.ready_task is not None:
                r.ready_task.cancel()
            await self._stop_replica(st, r.handle)
        st.replicas.clear()
        # Already-DRAINING replicas finish through their own drain tasks.

    # Idle linger before a drained replica dies: covers the router
    # routable-set cache window (Router.REFRESH_S) plus wire latency, so
    # late-routed requests bounce (re-route) instead of dying with the
    # actor. Only applied to live drains (rolling update / scale-down /
    # node drain) — app deletion kills without it.
    DRAIN_LINGER_S = 1.3

    async def _stop_replica(self, st, rep, linger_s: float = 0.0):
        timeout = st.config.graceful_shutdown_timeout_s
        try:
            await asyncio.wait_for(
                rep.drain.remote(timeout, linger_s).future(),
                timeout=timeout + linger_s + 2)
        except Exception:
            pass
        try:
            ray_tpu.kill(rep)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Replica lifecycle
    # ------------------------------------------------------------------
    async def _start_replica(self, st: _DeploymentState,
                             replaces: Optional[_ReplicaInfo] = None):
        from ray_tpu.serve.replica import ReplicaActor
        cfg = st.config
        opts = dict(cfg.ray_actor_options)
        opts.setdefault("num_cpus", 0.1)
        # Admission control lives in the replica (bounded queue + shed):
        # the actor's concurrency cap must sit ABOVE max_ongoing + queue
        # so queued requests reach the replica's gate — and control
        # methods (health, drain, metrics) never starve behind a full
        # request queue.
        queued = (cfg.max_queued_requests if cfg.max_queued_requests >= 0
                  else 2048)
        opts.setdefault("max_concurrency",
                        cfg.max_ongoing_requests + queued + 32)
        target_slice = ""
        if cfg.slice_spread and "scheduling_strategy" not in opts:
            strat, target_slice = await self._slice_spread_strategy(st)
            if strat is not None:
                opts["scheduling_strategy"] = strat
        cls = ray_tpu.remote(**opts)(ReplicaActor)
        limits = {"deployment": st.name,
                  "max_ongoing": cfg.max_ongoing_requests,
                  "max_queued": cfg.max_queued_requests,
                  "request_replay": cfg.request_replay,
                  # Replica-side SLO accounting (slow-request counter)
                  # needs the latency target; 0 disables.
                  "slo_latency_target_s":
                      cfg.slo_config.target_p99_s
                      if cfg.slo_config is not None else 0.0}
        rep = cls.remote(st.blob, cfg.user_config, limits)
        info = _ReplicaInfo(rep, st.version)
        info.replaces = replaces
        info.target_slice = target_slice
        st.replicas.append(info)
        st.list_version += 1
        info.ready_task = asyncio.ensure_future(self._wait_ready(st, info))
        return info

    async def _wait_ready(self, st: _DeploymentState, info: _ReplicaInfo):
        """Background readiness watcher: bounded, one per replica — a
        hung constructor stalls only its own watcher, never the
        reconcile loop (the health loop's startup grace reaps it)."""
        try:
            await asyncio.wait_for(
                info.handle.check_health.remote().future(),
                timeout=st.STARTUP_GRACE_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        # READY + swap in ONE sync block (no await between them): the
        # routable set must never publish a version where both the old
        # replica and its replacement serve — a client that already saw
        # the new version could be routed back to the old one.
        info.ever_healthy = True
        if info.state == REPLICA_STARTING:
            info.state = REPLICA_RUNNING
            st.list_version += 1
        old, info.replaces = info.replaces, None
        if old is not None and old in st.replicas:
            # Replace-then-drain: the replacement serves before the old
            # replica retires (rolling, never big-bang).
            self._begin_drain(st, old, "rolling update")
        try:
            info.node_id = await self._actor_node(info.handle)
        except Exception:  # noqa: BLE001 — placement info is best-effort
            pass

    def _begin_drain(self, st: _DeploymentState, r: _ReplicaInfo,
                     reason: str):
        """DRAINING: out of the routable set immediately; queued work is
        handed back to routers by the replica; in-flight finishes within
        graceful_shutdown_timeout_s; then the actor dies."""
        if r.state == REPLICA_DRAINING:
            return
        if r.ready_task is not None:
            r.ready_task.cancel()
        if r in st.replicas:
            st.replicas.remove(r)
        st.list_version += 1
        r.state = REPLICA_DRAINING
        st.draining.append(r)
        logger.info("draining replica %s of %s (%s)",
                    r.replica_id, st.name, reason)
        r.drain_task = asyncio.ensure_future(self._drain_and_stop(st, r))

    async def _drain_and_stop(self, st: _DeploymentState, r: _ReplicaInfo):
        await self._stop_replica(st, r.handle, linger_s=self.DRAIN_LINGER_S)
        if r in st.draining:
            st.draining.remove(r)

    # ------------------------------------------------------------------
    # Reconciliation
    # ------------------------------------------------------------------
    async def _reconcile_once(self):
        async with self._reconcile_lock:
            await self._reconcile_locked()

    async def _reconcile_locked(self):
        for st in list(self._deployments.values()):
            # Rolling update: replace stale-version replicas one at a
            # time — new replica first, old drained once it's READY.
            if not any(r.replaces is not None for r in st.replicas):
                stale = next(
                    (r for r in st.replicas
                     if r.version != st.version and not r.being_replaced),
                    None)
                if stale is not None:
                    stale.being_replaced = True
                    await self._start_replica(st, replaces=stale)
            # Scale to target (replacement replicas don't fill a slot).
            # Warm the worker pools FIRST: every deficit path — initial
            # deploy, queue-policy upscale, SLO-burn upscale, gang
            # failover — funnels through here, and the replica actors'
            # time-to-READY is bounded by worker spawn.
            deficit = st.target_num - len(st.active())
            if deficit > 0:
                await self._prestart_for(st, deficit)
            while len(st.active()) < st.target_num:
                await self._start_replica(st)
            while len(st.active()) > st.target_num:
                # Prefer retiring replicas that never served, then the
                # newest — oldest replicas are the proven ones.
                victims = sorted(
                    (r for r in st.active() if not r.being_replaced),
                    key=lambda r: (r.state == REPLICA_RUNNING, -r.started))
                if not victims:
                    break
                self._begin_drain(st, victims[0], "scale down")

    async def _prestart_for(self, st: _DeploymentState, deficit: int):
        """Send the GCS a prestart hint for `deficit` replica workers
        (throttled: the reconcile loop re-enters every ~0.5s while the
        replicas start — re-hinting the same deficit would just churn)."""
        now = time.time()
        if deficit <= st.last_prestart_n and now - st.last_prestart < 5.0:
            return
        st.last_prestart, st.last_prestart_n = now, deficit
        try:
            from ray_tpu._private import worker_api
            await worker_api.prestart_workers_async(
                worker_api.get_core(), deficit,
                (st.config.ray_actor_options or {}).get("runtime_env"))
        except Exception:  # noqa: BLE001 — a hint is best-effort
            logger.debug("prestart hint failed", exc_info=True)

    async def _reconcile_loop(self):
        while True:
            try:
                self._process_drain_notices()
                await self._reconcile_once()
                await self._health_check()
                await self._autoscale()
            except Exception:
                logger.exception("serve controller reconcile error")
            # Jittered so co-resident controllers/probes desynchronize;
            # the wake event short-circuits the sleep on drain notices.
            period = self.RECONCILE_PERIOD_S * random.uniform(0.7, 1.3)
            try:
                await asyncio.wait_for(self._wake.wait(), period)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    def _process_drain_notices(self):
        """Proactively drain replicas whose host got a drain/preemption
        notice (PR 1 two-phase drain, PR 4 gang drains): their queued
        work re-routes NOW instead of dying with the node at the
        deadline. The reconcile pass tops the count back up — on a
        healthy domain, thanks to slice spread."""
        try:
            from ray_tpu._private import worker_api
            events = worker_api.drain_events()
        except Exception:  # noqa: BLE001
            return
        new = events[self._drain_seen:]
        self._drain_seen = len(events)
        if not new:
            return
        draining_nodes = set()
        for ev in new:
            ids = ev.get("node_ids") or (
                [ev["node_id"]] if ev.get("node_id") is not None else [])
            draining_nodes.update(ids)
        if not draining_nodes:
            return
        for st in list(self._deployments.values()):
            for r in list(st.replicas):
                if r.node_id is not None and r.node_id in draining_nodes:
                    self._begin_drain(st, r, "node drain notice")

    async def _health_check(self):
        from ray_tpu import exceptions as exc
        now = time.monotonic()
        for st in list(self._deployments.values()):
            if now < st.next_health_check:
                continue
            st.next_health_check = now + (
                st.config.health_check_period_s * random.uniform(0.75, 1.25))

            async def check(r):
                try:
                    await asyncio.wait_for(
                        r.handle.check_health.remote().future(), timeout=5)
                    return True
                except exc.ActorDiedError:
                    return "dead"      # definitive: GCS marked it dead
                except Exception:
                    return False       # slow/unreachable: maybe starting
            # Probe all replicas concurrently: serial checks would make one
            # slow/dead replica delay the whole reconcile pass by its
            # timeout multiplied by the replica count.
            oks = await asyncio.gather(*[check(r) for r in st.replicas])
            for i, r in reversed(list(enumerate(st.replicas))):
                ok = oks[i]
                if ok is True:
                    r.ever_healthy = True
                    if r.state == REPLICA_STARTING:
                        r.state = REPLICA_RUNNING
                        st.list_version += 1
                    continue
                # A replica that has never come up yet may simply still be
                # starting (worker spawn under load): give it a grace
                # period before declaring it dead — unless its death is
                # definitive (a replica can crash before its first health
                # check ever succeeds; waiting out the grace would stall
                # recovery for a minute).
                if (ok is False and not r.ever_healthy
                        and now - r.started < st.STARTUP_GRACE_S):
                    continue
                self._drop_dead_replica(st, r)
        # reconcile_once (caller loop) will top the count back up

    def _drop_dead_replica(self, st: _DeploymentState, r: _ReplicaInfo):
        if r in st.replicas:
            st.replicas.remove(r)
        st.list_version += 1
        if r.ready_task is not None:
            r.ready_task.cancel()
        # Untangle rolling-update links so the swap machinery retries.
        if r.replaces is not None:
            r.replaces.being_replaced = False
            r.replaces = None
        for other in st.replicas:
            if other.replaces is r:
                other.replaces = None
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass

    async def _autoscale(self):
        now = time.monotonic()
        for st in list(self._deployments.values()):
            asc = st.config.autoscaling_config
            if (asc is None and st.slo is None) or not st.replicas:
                continue

            async def metrics(r):
                try:
                    return await asyncio.wait_for(
                        r.handle.get_metrics.remote().future(), timeout=5)
                except Exception:
                    return None
            results = await asyncio.gather(
                *[metrics(r) for r in st.replicas])
            polled = {r.replica_id: m
                      for r, m in zip(st.replicas, results) if m}
            # SLO burn: evaluated every pass (gauges/violations export
            # even without autoscaling); with autoscaling it scales UP on
            # sustained burn — latency pressure fires before the bounded
            # queue fills, so burn-driven capacity lands before a single
            # request is shed.
            if st.slo is not None and polled:
                st.slo.ingest(polled)
                verdict = st.slo.evaluate()
                if (verdict["violating"] and asc is not None
                        and st.target_num < asc.max_replicas
                        and now - st.last_slo_scale
                        >= st.config.slo_config.upscale_cooldown_s):
                    logger.info(
                        "SLO burn autoscale %s: %d -> %d (burn fast=%.1f "
                        "slow=%.1f)", st.name, st.target_num,
                        st.target_num + 1, verdict["fast"],
                        verdict["slow"])
                    st.target_num += 1
                    st.last_slo_scale = now
                    st.last_scale_change = now
                    continue  # burn owns this tick: no queue downscale
                if verdict["violating"]:
                    # Still burning (at max, or cooling down): never let
                    # the queue-depth policy scale DOWN a burning
                    # deployment.
                    st.last_scale_change = now
                    continue
            if asc is None:
                continue
            # Queued requests count toward pressure: with replica-side
            # admission queues, "ongoing" alone under-reports load.
            total = sum(m["ongoing"] + m.get("queued", 0)
                        for m in polled.values())
            desired = asc.decide(len(st.active()), total)
            delay = (asc.upscale_delay_s if desired > st.target_num
                     else asc.downscale_delay_s)
            if desired != st.target_num:
                if now - st.last_scale_change >= delay:
                    logger.info("autoscale %s: %d -> %d (ongoing=%.1f)",
                                st.name, st.target_num, desired, total)
                    st.target_num = desired
                    st.last_scale_change = now
            else:
                st.last_scale_change = now

    # ------------------------------------------------------------------
    # Slice fault-domain spread
    # ------------------------------------------------------------------
    async def _slice_domains(self):
        now = time.monotonic()
        if now - self._nodes_ts < 2.0:
            return self._domains
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        infos = await core.gcs.request("get_all_nodes", {})
        domains: Dict[str, list] = {}
        node_slice: Dict[Any, str] = {}
        for n in infos:
            sid = getattr(n, "slice_id", "")
            if not sid:
                continue
            node_slice[n.node_id] = sid
            if n.alive and not getattr(n, "draining", False):
                domains.setdefault(sid, []).append(n)
        self._domains = domains
        self._node_slice = node_slice
        self._nodes_ts = now
        return domains

    async def _slice_spread_strategy(self, st: _DeploymentState):
        """Anti-affinity across TPU-slice fault domains: pick the domain
        hosting the fewest of this deployment's replicas, soft node
        affinity into it — one slice preemption can then never take the
        whole deployment."""
        try:
            domains = await self._slice_domains()
        except Exception:  # noqa: BLE001 — placement hint is best-effort
            return None, ""
        if len(domains) < 2:
            return None, ""
        counts = {s: 0 for s in domains}
        for r in st.replicas:
            sid = r.target_slice or self._node_slice.get(r.node_id, "")
            if sid in counts:
                counts[sid] += 1
        target = min(sorted(counts), key=lambda s: counts[s])
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        node = domains[target][0]
        return NodeAffinitySchedulingStrategy(node.node_id, soft=True), target

    async def _actor_node(self, handle):
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        info = await core.gcs.request(
            "get_actor_info", {"actor_id": handle._actor_id})
        return getattr(info, "node_id", None)

    # ------------------------------------------------------------------
    # Queries
    # ------------------------------------------------------------------
    def get_replicas(self, app_name: str, deployment_name: str):
        st = self._deployments.get((app_name, deployment_name))
        if st is None:
            return (0, [])
        return (st.list_version, [r.handle for r in st.replicas])

    def get_routing(self, app_name: str, deployment_name: str):
        """Routable replica set + the routing-relevant config bits.

        RUNNING replicas only — except cold start (none RUNNING yet),
        where STARTING replicas are offered so requests queue on a
        booting replica instead of failing."""
        st = self._deployments.get((app_name, deployment_name))
        if st is None:
            return {"version": 0, "replicas": [], "config": {}}
        routable = [r for r in st.replicas if r.state == REPLICA_RUNNING]
        if not routable:
            routable = list(st.replicas)
        return {
            "version": st.list_version,
            "replicas": [(r.replica_id, r.handle) for r in routable],
            "config": {
                "deployment": st.name,
                "request_replay": st.config.request_replay,
                "request_timeout_s": st.config.request_timeout_s,
            },
        }

    def get_route_table(self):
        return dict(self._routes)

    def status(self):
        out = {}
        for (app, name), st in self._deployments.items():
            row = {
                "target": st.target_num,
                "running": len(st.replicas),
                "ready": sum(1 for r in st.replicas
                             if r.state == REPLICA_RUNNING),
                "draining": len(st.draining),
                "version": st.version,
            }
            if st.slo is not None:
                row["slo"] = {
                    "burn_fast": round(st.slo.burn_fast, 3),
                    "burn_slow": round(st.slo.burn_slow, 3),
                    "violating": st.slo.violating,
                    "violations": st.slo.violations,
                }
            out.setdefault(app, {})[name] = row
        return out

    async def ensure_proxy(self, host: str, port: int):
        if self._proxy is None:
            from ray_tpu.serve.proxy import ProxyActor
            cls = ray_tpu.remote(num_cpus=0.1)(ProxyActor)
            self._proxy = cls.remote(host, port)
            await self._proxy.ready.remote()
        return True

    async def ensure_grpc_proxy(self, host: str, port: int) -> int:
        """Start the binary-RPC ingress (reference: gRPCProxy); returns the
        bound port."""
        if getattr(self, "_grpc_proxy", None) is None:
            from ray_tpu.serve.grpc_proxy import GrpcProxyActor
            cls = ray_tpu.remote(num_cpus=0.1)(GrpcProxyActor)
            actor = cls.remote(host, port)
            try:
                self._grpc_port = await actor.ready.remote()
            except Exception:
                # Failed startup (e.g. port in use) must stay retryable.
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
                raise
            self._grpc_host = host
            self._grpc_proxy = actor
        return self._grpc_port

    def get_grpc_address(self) -> str:
        if getattr(self, "_grpc_proxy", None) is None:
            raise RuntimeError("binary-RPC ingress not started; "
                               "serve.start(grpc_proxy=True)")
        return f"{self._grpc_host}:{self._grpc_port}"

    async def shutdown(self):
        for key in list(self._deployments):
            await self._remove_deployment(key)
        if getattr(self, "_grpc_proxy", None) is not None:
            try:
                ray_tpu.kill(self._grpc_proxy)
            except Exception:
                pass
        if self._proxy is not None:
            try:
                ray_tpu.kill(self._proxy)
            except Exception:
                pass
            self._proxy = None
        return True
