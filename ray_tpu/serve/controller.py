"""ServeController: the reconciliation brain.

Reference parity: python/ray/serve/_private/controller.py:91 and
deployment_state.py:1226 (DeploymentState/DeploymentStateManager). One named
actor holds target state per app/deployment, reconciles replicas (create,
remove, rolling-update by version), health-checks them, and applies
queue-depth autoscaling. Routers poll get_replicas() with a version counter
(the long-poll analogue).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _ReplicaInfo:
    def __init__(self, handle, version: str):
        self.handle = handle
        self.version = version
        self.started = time.monotonic()
        self.ever_healthy = False


class _DeploymentState:
    STARTUP_GRACE_S = 60.0

    def __init__(self, app_name: str, name: str, blob: bytes, config,
                 version: str):
        self.app_name = app_name
        self.name = name
        self.blob = blob
        self.config = config
        self.version = version
        self.replicas: List[_ReplicaInfo] = []
        self.target_num = config.num_replicas
        self.list_version = 0              # bumped on any replica-set change
        self.last_scale_change = 0.0


class ServeController:
    def __init__(self):
        self._deployments: Dict[tuple, _DeploymentState] = {}
        self._routes: Dict[str, tuple] = {}  # route_prefix -> (app, ingress)
        self._proxy = None
        self._reconcile_task = None
        self._started = False

    async def _ensure_loops(self):
        if not self._started:
            self._started = True
            self._reconcile_task = asyncio.ensure_future(
                self._reconcile_loop())

    # ------------------------------------------------------------------
    # Deployment API
    # ------------------------------------------------------------------
    async def deploy_app(self, app_name: str, deployments: List[dict],
                         route_prefix: Optional[str], ingress: str):
        """deployments: [{name, blob, config, version}]"""
        await self._ensure_loops()
        incoming = set()
        for d in deployments:
            key = (app_name, d["name"])
            incoming.add(key)
            cur = self._deployments.get(key)
            if cur is None:
                self._deployments[key] = _DeploymentState(
                    app_name, d["name"], d["blob"], d["config"], d["version"])
            else:
                cur.blob = d["blob"]
                cur.config = d["config"]
                cur.version = d["version"]
                cur.target_num = d["config"].num_replicas
        # Remove deployments no longer in the app.
        for key in [k for k in self._deployments
                    if k[0] == app_name and k not in incoming]:
            await self._remove_deployment(key)
        if route_prefix is not None:
            self._routes[route_prefix] = (app_name, ingress)
        await self._reconcile_once()
        return True

    async def delete_app(self, app_name: str):
        for key in [k for k in self._deployments if k[0] == app_name]:
            await self._remove_deployment(key)
        self._routes = {r: v for r, v in self._routes.items()
                        if v[0] != app_name}
        return True

    async def _remove_deployment(self, key):
        st = self._deployments.pop(key, None)
        if st is None:
            return
        for r in st.replicas:
            await self._stop_replica(st, r.handle)

    async def _stop_replica(self, st, rep):
        try:
            await asyncio.wait_for(
                rep.drain.remote(st.config.graceful_shutdown_timeout_s).future(),
                timeout=st.config.graceful_shutdown_timeout_s + 2)
        except Exception:
            pass
        try:
            ray_tpu.kill(rep)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Reconciliation
    # ------------------------------------------------------------------
    async def _start_replica(self, st: _DeploymentState):
        from ray_tpu.serve.replica import ReplicaActor
        opts = dict(st.config.ray_actor_options)
        opts.setdefault("num_cpus", 0.1)
        opts.setdefault("max_concurrency", st.config.max_ongoing_requests)
        cls = ray_tpu.remote(**opts)(ReplicaActor)
        rep = cls.remote(st.blob, st.config.user_config)
        info = _ReplicaInfo(rep, st.version)
        st.replicas.append(info)
        st.list_version += 1
        return info

    async def _reconcile_once(self):
        for st in list(self._deployments.values()):
            # Rolling update: replace replicas built from an older version.
            stale = [i for i, r in enumerate(st.replicas)
                     if r.version != st.version]
            for i in sorted(stale, reverse=True):
                old = st.replicas[i]
                del st.replicas[i]
                st.list_version += 1
                new = await self._start_replica(st)
                # Wait for the new replica to come up before killing the old
                # one (rolling, not big-bang).
                try:
                    await asyncio.wait_for(
                        new.handle.check_health.remote().future(), timeout=30)
                    new.ever_healthy = True
                except Exception:
                    pass
                await self._stop_replica(st, old.handle)
            # Scale to target.
            while len(st.replicas) < st.target_num:
                await self._start_replica(st)
            while len(st.replicas) > st.target_num:
                r = st.replicas.pop()
                st.list_version += 1
                await self._stop_replica(st, r.handle)

    async def _reconcile_loop(self):
        while True:
            try:
                await self._reconcile_once()
                await self._health_check()
                await self._autoscale()
            except Exception:
                logger.exception("serve controller reconcile error")
            await asyncio.sleep(0.5)

    async def _health_check(self):
        from ray_tpu import exceptions as exc
        now = time.monotonic()
        for st in list(self._deployments.values()):
            async def check(r):
                try:
                    await asyncio.wait_for(
                        r.handle.check_health.remote().future(), timeout=5)
                    return True
                except exc.ActorDiedError:
                    return "dead"      # definitive: GCS marked it dead
                except Exception:
                    return False       # slow/unreachable: maybe starting
            # Probe all replicas concurrently: serial checks would make one
            # slow/dead replica delay the whole reconcile pass by its
            # timeout multiplied by the replica count.
            oks = await asyncio.gather(*[check(r) for r in st.replicas])
            for i, r in reversed(list(enumerate(st.replicas))):
                ok = oks[i]
                if ok is True:
                    r.ever_healthy = True
                    continue
                # A replica that has never come up yet may simply still be
                # starting (worker spawn under load): give it a grace
                # period before declaring it dead — unless its death is
                # definitive (a replica can crash before its first health
                # check ever succeeds; waiting out the grace would stall
                # recovery for a minute).
                if (ok is False and not r.ever_healthy
                        and now - r.started < st.STARTUP_GRACE_S):
                    continue
                del st.replicas[i]
                st.list_version += 1
                try:
                    ray_tpu.kill(r.handle)
                except Exception:
                    pass
        # reconcile_once (caller loop) will top the count back up

    async def _autoscale(self):
        now = time.monotonic()
        for st in list(self._deployments.values()):
            asc = st.config.autoscaling_config
            if asc is None or not st.replicas:
                continue
            async def metrics(r):
                try:
                    return await asyncio.wait_for(
                        r.handle.get_metrics.remote().future(), timeout=5)
                except Exception:
                    return None
            results = await asyncio.gather(
                *[metrics(r) for r in st.replicas])
            total = sum(m["ongoing"] for m in results if m)
            desired = asc.decide(len(st.replicas), total)
            delay = (asc.upscale_delay_s if desired > st.target_num
                     else asc.downscale_delay_s)
            if desired != st.target_num:
                if now - st.last_scale_change >= delay:
                    logger.info("autoscale %s: %d -> %d (ongoing=%.1f)",
                                st.name, st.target_num, desired, total)
                    st.target_num = desired
                    st.last_scale_change = now
            else:
                st.last_scale_change = now

    # ------------------------------------------------------------------
    # Queries
    # ------------------------------------------------------------------
    def get_replicas(self, app_name: str, deployment_name: str):
        st = self._deployments.get((app_name, deployment_name))
        if st is None:
            return (0, [])
        return (st.list_version, [r.handle for r in st.replicas])

    def get_route_table(self):
        return dict(self._routes)

    def status(self):
        out = {}
        for (app, name), st in self._deployments.items():
            out.setdefault(app, {})[name] = {
                "target": st.target_num,
                "running": len(st.replicas),
                "version": st.version,
            }
        return out

    async def ensure_proxy(self, host: str, port: int):
        if self._proxy is None:
            from ray_tpu.serve.proxy import ProxyActor
            cls = ray_tpu.remote(num_cpus=0.1)(ProxyActor)
            self._proxy = cls.remote(host, port)
            await self._proxy.ready.remote()
        return True

    async def ensure_grpc_proxy(self, host: str, port: int) -> int:
        """Start the binary-RPC ingress (reference: gRPCProxy); returns the
        bound port."""
        if getattr(self, "_grpc_proxy", None) is None:
            from ray_tpu.serve.grpc_proxy import GrpcProxyActor
            cls = ray_tpu.remote(num_cpus=0.1)(GrpcProxyActor)
            actor = cls.remote(host, port)
            try:
                self._grpc_port = await actor.ready.remote()
            except Exception:
                # Failed startup (e.g. port in use) must stay retryable.
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
                raise
            self._grpc_host = host
            self._grpc_proxy = actor
        return self._grpc_port

    def get_grpc_address(self) -> str:
        if getattr(self, "_grpc_proxy", None) is None:
            raise RuntimeError("binary-RPC ingress not started; "
                               "serve.start(grpc_proxy=True)")
        return f"{self._grpc_host}:{self._grpc_port}"

    async def shutdown(self):
        for key in list(self._deployments):
            await self._remove_deployment(key)
        if getattr(self, "_grpc_proxy", None) is not None:
            try:
                ray_tpu.kill(self._grpc_proxy)
            except Exception:
                pass
        if self._proxy is not None:
            try:
                ray_tpu.kill(self._proxy)
            except Exception:
                pass
            self._proxy = None
        return True
