"""ServeController: the reconciliation brain.

Reference parity: python/ray/serve/_private/controller.py:91 and
deployment_state.py:1226 (DeploymentState/DeploymentStateManager). One named
actor holds target state per app/deployment, reconciles replicas (create,
remove, rolling-update by version), health-checks them, and applies
queue-depth autoscaling. Routers poll get_routing() with a version counter
(the long-poll analogue).

Durable control plane (reference: the controller checkpoints to the GCS
KV and RECOVERS running replicas, it never restarts them):

- Every target-state mutation (deploy / delete / scale / autoscale
  decision) persists a schema-versioned record to the GCS KV (``serve``
  namespace, serve/persistence.py) BEFORE the mutation's routing or
  replica effects publish, and every live replica keeps a registry row
  (actor id, version, node/slice, rolling-update swap link).
- The controller is a restartable detached named actor
  (max_restarts=-1): a crash or preemption restart re-runs the
  constructor, which loads target state; the first call then REATTACHES
  the still-live ReplicaActors from the registry and reconciles — only
  version-mismatched or unhealthy replicas are replaced, healthy ones
  keep serving without a blip. An in-flight rolling update resumes
  replace-then-drain from its persisted swap link instead of routing
  two versions or restarting the rollout.
- Replicas and proxies are detached too: a controller death must not
  cascade into its children through owner cleanup, and routers/proxies
  serve from their last-known routing tables (bounded staleness) right
  through the outage — a controller death alone never drops a request.

Replica lifecycle (serve-under-fire):

    STARTING --ready--> RUNNING --drain--> DRAINING --> killed

- STARTING replicas are routable only while NO replica is RUNNING (cold
  start: queueing on a starting replica beats failing), so a rolling
  update never routes onto a not-yet-ready replacement.
- Rolling updates and scale-downs are replace-then-drain: the new
  replica must reach RUNNING before the old one drains; draining stops
  new dispatch, hands queued work back to the router, finishes in-flight
  requests within graceful_shutdown_timeout_s, then the actor dies.
- Node drain notices (PR 1's two-phase drain / slice gang drains) are
  consumed from this process's drain-event log: replicas on a draining
  node drain proactively instead of dying with the host.
- Replicas spread across TPU-slice fault domains (config.slice_spread)
  so one slice preemption never takes the whole deployment.
- Readiness is watched by per-replica background tasks — a hung
  constructor can never stall the reconcile loop — and the reconcile /
  health-check periods are jittered so co-resident controllers and
  probe bursts desynchronize.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve import persistence

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"

# Reserved actor NAMESPACE for every actor the serve control plane
# creates (replicas, HTTP/binary proxies). The recovery orphan sweep
# keys on membership in this namespace plus absence from the KV
# registry — never on class names — so a user actor class literally
# named "ReplicaActor" can never be mistaken for serve's and killed.
SERVE_ACTOR_NAMESPACE = "_ray_tpu_serve"

REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_DRAINING = "DRAINING"


def _recoveries_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_controller_recoveries_total",
        "serve controller restarts that recovered persisted target state "
        "from the GCS KV (reattach-first: healthy replicas kept serving)")


def _reattached_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_replicas_reattached_total",
        "still-live replicas a recovered controller reattached from the "
        "KV registry instead of restarting",
        tag_keys=("Deployment",))


def _replaced_counter():
    from ray_tpu.util import metrics
    return metrics.Counter(
        "ray_tpu_serve_replicas_replaced_total",
        "registry replicas a recovered controller found dead/unhealthy "
        "and replaced (the non-reattachable remainder)",
        tag_keys=("Deployment",))


class _ReplicaInfo:
    def __init__(self, handle, version: str):
        self.handle = handle
        self.version = version
        self.replica_id = uuid.uuid4().hex[:12]
        self.started = time.monotonic()
        self.ever_healthy = False
        self.state = REPLICA_STARTING
        self.node_id = None            # resolved once READY
        self.target_slice = ""         # slice domain picked at start
        # Multiplexing: model ids resident in the replica's LRU cache,
        # polled with health checks and published via get_routing.
        self.resident_models: frozenset = frozenset()
        self.ready_task: Optional[asyncio.Task] = None
        self.drain_task: Optional[asyncio.Task] = None
        # Rolling update: the old replica this one replaces — retired
        # (drained) only once this replica reaches RUNNING.
        self.replaces: Optional["_ReplicaInfo"] = None
        self.being_replaced = False


class _DeploymentState:
    STARTUP_GRACE_S = 60.0

    def __init__(self, app_name: str, name: str, blob: bytes, config,
                 version: str):
        self.app_name = app_name
        self.name = name
        self.blob = blob
        self.config = config
        self.version = version
        self.replicas: List[_ReplicaInfo] = []   # STARTING / RUNNING
        self.draining: List[_ReplicaInfo] = []   # retiring, not routable
        self.target_num = config.num_replicas
        self.list_version = 0              # bumped on any replica-set change
        self.last_scale_change = 0.0
        self.next_health_check = 0.0
        self.slo = None                    # DeploymentSLO when configured
        self.last_slo_scale = 0.0
        self.last_slo_downscale = 0.0
        # Worker prestart-hint throttle (scale-up warm-up).
        self.last_prestart = 0.0
        self.last_prestart_n = 0
        self._rebuild_slo()

    def _rebuild_slo(self):
        if self.config.slo_config is None:
            self.slo = None
            return
        from ray_tpu.serve.slo import DeploymentSLO
        self.slo = DeploymentSLO(self.name, self.config.slo_config)

    def active(self) -> List[_ReplicaInfo]:
        """Replicas that fill a target slot (replacements don't — they
        take their predecessor's slot at swap time)."""
        return [r for r in self.replicas if r.replaces is None]


class ServeController:
    RECONCILE_PERIOD_S = 0.5
    PROXY_WATCH_PERIOD_S = 5.0

    def __init__(self):
        self._deployments: Dict[tuple, _DeploymentState] = {}
        self._routes: Dict[str, tuple] = {}  # route_prefix -> (app, ingress)
        self._proxy = None
        self._reconcile_task = None
        self._boot_task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        # deploy_app's inline reconcile and the background loop interleave
        # (replica starts await the slice-domain lookup): without mutual
        # exclusion both can top up the same deployment and overshoot.
        self._reconcile_lock = asyncio.Lock()
        # Control-plane API mutations (deploy/delete/shutdown) serialize:
        # the route table and proxy bindings are read-modify-write over
        # awaits, and interleaved calls would lose updates.
        self._api_lock = asyncio.Lock()
        self._proxy_lock = asyncio.Lock()
        self._drain_seen = 0               # index into drain_events()
        self._domains: Dict[str, list] = {}
        self._node_slice: Dict[Any, str] = {}
        self._nodes_ts = 0.0
        self._next_proxy_watch = 0.0
        self._proxy_watch_task: Optional[asyncio.Task] = None
        # Operator knobs (serve.start(config=...)), recovered from the KV
        # before any recovery machinery that consumes them runs.
        from ray_tpu.serve.config import ServeConfig
        self._serve_config = ServeConfig()
        # Durable control plane: write-ahead store + recovery bookkeeping.
        self._persist = persistence.ServeStateStore()
        self._recoveries_cum = 0           # KV-backed, across restarts
        self._recover_t0 = 0.0             # >0 => this instance recovered
        self._reattached_total = 0
        self._replaced_total = 0
        self._pending_reattach: Dict[tuple, List[dict]] = {}
        self._proxy_rec: Dict[str, dict] = {}
        self._known_actor_ids: set = set()   # registry + proxy actor ids
        # The constructor runs on the worker's exec pool (no loop):
        # blocking KV loads are legal here, and method calls can't land
        # until it returns — so by the time anyone queries routing, the
        # target state below is complete.
        self._load_state()
        if self._recover_t0:
            # Self-driven recovery: a restarted controller must not wait
            # for external traffic to kick its boot — the proxy may be
            # dead too, leaving NOBODY to call us, and recovery is what
            # re-arms the proxy. Schedule boot on the worker's core loop
            # directly from the constructor.
            try:
                from ray_tpu._private import worker_api
                core = worker_api.peek_core()
                if core is not None:
                    asyncio.run_coroutine_threadsafe(
                        self._ensure_loops(), core.loop)
            except Exception:  # noqa: BLE001 — first call still boots
                logger.debug("self-boot kick failed", exc_info=True)

    # ------------------------------------------------------------------
    # Recovery: load persisted state (sync, constructor) + reattach
    # ------------------------------------------------------------------
    def _load_state(self):
        try:
            records = self._persist.load_all()
        except Exception:  # noqa: BLE001 — KV unreachable: start empty
            logger.exception("serve state load failed; starting fresh")
            return
        meta = records.pop(b"meta", None) or {}
        self._recoveries_cum = int(meta.get("recoveries", 0))
        cfg_rec = records.pop(persistence.CONFIG_KEY, None)
        if cfg_rec:
            self._apply_serve_config(cfg_rec)
        targets = {k: r for k, r in records.items()
                   if k.startswith(b"target/")}
        apps = {k: r for k, r in records.items() if k.startswith(b"app/")}
        has_rows = any(k.startswith(b"replica/") for k in records)
        if (not targets and not apps and not has_rows
                and persistence.PROXIES_KEY not in records):
            return  # fresh cluster: nothing to recover
        self._reconcile_app_snapshots(apps, targets, records)
        # Orphan replica rows with NO target (crash mid-delete) still
        # demand a recovery pass: target-less rows are killed + GC'd.
        self._recover_t0 = time.time()
        self._recoveries_cum += 1
        # Per-record fault isolation: one torn/foreign record must skip,
        # never crash — a constructor exception would crash-loop the
        # max_restarts=-1 controller on the same record forever.
        for rec in targets.values():
            try:
                key = (rec["app"], rec["name"])
                st = _DeploymentState(rec["app"], rec["name"], rec["blob"],
                                      rec["config"], rec["version"])
                self._apply_target_record(st, rec)
                self._deployments[key] = st
            except Exception:  # noqa: BLE001
                logger.exception("skipping unreadable target record")
        routes = records.get(persistence.ROUTES_KEY)
        if routes:
            self._routes = dict(routes.get("routes") or {})
        for k, rec in records.items():
            if not k.startswith(b"replica/"):
                continue
            try:
                dkey = (rec["app"], rec["deployment"])
                self._known_actor_ids.add(rec["actor_id"])
                self._pending_reattach.setdefault(dkey, []).append(rec)
            except Exception:  # noqa: BLE001
                logger.exception("skipping unreadable replica row")
        self._proxy_rec = dict(records.get(persistence.PROXIES_KEY) or {})
        for rec in self._proxy_rec.values():
            if isinstance(rec, dict) and "actor_id" in rec:
                self._known_actor_ids.add(rec["actor_id"])
        try:
            self._persist.put_sync(b"meta",
                                   {"recoveries": self._recoveries_cum})
        except Exception:  # noqa: BLE001
            logger.debug("recovery-count persist failed", exc_info=True)
        try:
            _recoveries_counter().inc()
        except Exception:  # noqa: BLE001 — metrics never block recovery
            pass
        logger.info(
            "serve controller recovering: %d deployment(s), %d registry "
            "replica row(s), %d route(s) (recovery #%d)",
            len(targets), sum(len(v) for v in self._pending_reattach.values()),
            len(self._routes), self._recoveries_cum)

    def _reconcile_app_snapshots(self, apps: dict, targets: dict,
                                 records: dict) -> None:
        """App-atomic recovery: the per-app snapshot blob (ONE KV value,
        written before any per-deployment record) is authoritative for
        app MEMBERSHIP and per-deployment VERSIONS. A crash between a
        deploy's snapshot and its per-deployment writes leaves
        stragglers: records missing or carrying the PREVIOUS version
        adopt the snapshot's copy, and records for deployments the
        snapshot no longer lists (a removal that crashed mid-way) are
        dropped — never a cross-deployment version mix. Records whose
        version matches keep their own target_num (scales after the
        deploy are per-deployment state, not snapshot state)."""
        for snap in apps.values():
            try:
                app = snap["app"]
                snap_recs = {r["name"]: r
                             for r in (snap.get("deployments") or [])}
            except Exception:  # noqa: BLE001 — torn snapshot: skip
                logger.exception("skipping unreadable app snapshot")
                continue
            for name, rec in snap_recs.items():
                tkey = persistence.target_key(app, name)
                cur = targets.get(tkey)
                if cur is None or cur.get("version") != rec.get("version"):
                    logger.warning(
                        "app %s/%s: adopting snapshot record (crash "
                        "mid-deploy left %s)", app, name,
                        "no record" if cur is None else
                        f"version {cur.get('version')!r}")
                    targets[tkey] = dict(rec)
                    try:
                        self._persist.put_sync(tkey, dict(rec))
                    except Exception:  # noqa: BLE001
                        logger.debug("snapshot record re-persist failed",
                                     exc_info=True)
            prefix = f"target/{app}/".encode()
            for tkey in [t for t in list(targets)
                         if t.startswith(prefix)]:
                if targets[tkey].get("name") not in snap_recs:
                    targets.pop(tkey)
                    try:
                        self._persist.delete_sync(tkey)
                    except Exception:  # noqa: BLE001
                        logger.debug("stale target delete failed",
                                     exc_info=True)
            # Route binding rides the snapshot too: a crash before the
            # ROUTES_KEY write must not leave the app unroutable.
            rp, ingress = snap.get("route_prefix"), snap.get("ingress", "")
            if rp:
                routes_rec = records.get(persistence.ROUTES_KEY) or {}
                routes = dict(routes_rec.get("routes") or {})
                if routes.get(rp) != (app, ingress):
                    routes[rp] = (app, ingress)
                    records[persistence.ROUTES_KEY] = {"routes": routes}
                    try:
                        self._persist.put_sync(persistence.ROUTES_KEY,
                                               {"routes": routes})
                    except Exception:  # noqa: BLE001
                        logger.debug("route re-persist failed",
                                     exc_info=True)

    def _apply_serve_config(self, fields: dict) -> None:
        """Overlay persisted/operator ServeConfig fields onto defaults —
        unknown keys are ignored (forward compat with newer writers)."""
        for k in ("recovery_probe_timeout_s",):
            if k in fields:
                try:
                    setattr(self._serve_config, k, float(fields[k]))
                except (TypeError, ValueError):
                    pass

    async def set_serve_config(self, fields: dict) -> bool:
        """serve.start(config=ServeConfig(...)): persist, then apply."""
        await self._ensure_loops()
        rec = {k: v for k, v in (fields or {}).items()
               if not k.startswith("_")}
        await self._persist.put(persistence.CONFIG_KEY, rec)
        self._apply_serve_config(rec)
        return True

    @staticmethod
    def _apply_target_record(st: _DeploymentState, rec: dict):
        """The ONE place (besides _set_target) allowed to write target
        fields — enforced by scripts/check_serve_persistence.py."""
        st.blob = rec["blob"]
        st.config = rec["config"]
        st.version = rec["version"]
        st.target_num = rec["target_num"]
        st._rebuild_slo()

    async def _recover(self):
        """Reattach-first recovery: probe every registry row, keep the
        healthy replicas serving (no restart), replace the dead, resume
        any in-flight rolling update from its persisted swap link."""
        if not self._recover_t0:
            return
        from ray_tpu.util import tracing
        span = tracing.start_span("serve:controller_recovery", None, "")
        pending, self._pending_reattach = self._pending_reattach, {}
        for dkey, rows in pending.items():
            st = self._deployments.get(dkey)
            if st is None:
                # Rows for a deployment whose target record was deleted
                # mid-shutdown: finish the job.
                for row in rows:
                    self._kill_registry_actor(row)
                    self._persist.delete_soon(persistence.replica_key(
                        row["app"], row["deployment"], row["replica_id"]))
                continue
            try:
                await self._reattach_deployment(st, rows)
            except Exception:  # noqa: BLE001 — never wedge recovery
                logger.exception("reattach failed for %s; replicas will "
                                 "be replaced by reconcile", dkey)
        # Sweep BEFORE proxy reattach: an orphan proxy from a crash in
        # the create-before-persist window may still hold the bind port
        # the recreation below needs.
        await self._sweep_orphan_actors()
        await self._reattach_proxies()
        try:
            tracing.export_span(span)
        except Exception:  # noqa: BLE001
            pass
        logger.info("serve controller recovery done: %d reattached, "
                    "%d replaced", self._reattached_total,
                    self._replaced_total)

    async def _sweep_orphan_actors(self):
        """Close the create-before-persist window: a crash between a
        detached actor's creation (replica in _start_replica, proxy in
        the ensure paths) and its KV record leaves a live actor no
        registry row references — owner cleanup no longer reaps it
        (detached), so recovery must. Runs before the reconcile loop
        starts creating anything new, so every legitimate serve actor is
        either in the loaded registry or a reattached proxy binding.

        Candidate identity is the controller-owned actor NAMESPACE
        (every serve-created actor is born into SERVE_ACTOR_NAMESPACE),
        never the class name: a user actor class literally named
        "ReplicaActor" lives in the user's namespace and is invisible
        to this sweep."""
        from ray_tpu._private import worker_api
        from ray_tpu.actor import ActorHandle
        core = worker_api.peek_core()
        if core is None:
            return
        try:
            infos = await core.gcs.request("get_all_actors", {})
        except Exception:  # noqa: BLE001 — sweep is best-effort
            return
        for info in self._orphan_candidates(infos):
            logger.warning(
                "killing orphaned serve actor %s (%s): created but never "
                "registered before a controller crash",
                info.actor_id.hex()[:12], info.class_name)
            try:
                ray_tpu.kill(ActorHandle._from_actor_info(info))
            except Exception:  # noqa: BLE001
                pass

    def _orphan_candidates(self, infos) -> list:
        """Sweep policy, isolated for unit tests: alive + born in the
        serve namespace + absent from the registry/known set. Class
        names are deliberately NOT consulted."""
        from ray_tpu._private.common import ACTOR_DEAD
        return [info for info in infos
                if getattr(info, "namespace", "") == SERVE_ACTOR_NAMESPACE
                and info.state != ACTOR_DEAD
                and info.actor_id not in self._known_actor_ids]

    @staticmethod
    def _kill_registry_actor(row: dict):
        try:
            from ray_tpu.actor import ActorHandle
            ray_tpu.kill(ActorHandle(row["actor_id"],
                                     class_name="ReplicaActor"))
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    async def _reattach_deployment(self, st: _DeploymentState,
                                   rows: List[dict]):
        from ray_tpu._private import worker_api
        from ray_tpu._private.common import (ACTOR_DEAD, ACTOR_PENDING,
                                             ACTOR_RESTARTING)
        from ray_tpu.actor import ActorHandle
        core = worker_api.peek_core()
        if core is None:
            return  # bare unit tests: reconcile starts replicas fresh

        probe_timeout = self._serve_config.recovery_probe_timeout_s

        async def probe(row):
            try:
                info = await core.gcs.request(
                    "get_actor_info", {"actor_id": row["actor_id"]})
            except Exception:  # noqa: BLE001
                info = None
            if info is None or info.state == ACTOR_DEAD:
                return row, None, "dead"
            handle = ActorHandle._from_actor_info(info)
            if info.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                # Constructor still running (crash landed mid-start):
                # reattach as STARTING with fresh startup grace.
                return row, handle, "starting"
            try:
                await asyncio.wait_for(
                    handle.check_health.remote().future(),
                    timeout=probe_timeout)
                return row, handle, "healthy"
            except Exception:  # noqa: BLE001
                return row, handle, "unhealthy"

        results = await asyncio.gather(*(probe(r) for r in rows))
        by_rid: Dict[str, tuple] = {}
        for row, handle, verdict in results:
            key = persistence.replica_key(row["app"], row["deployment"],
                                          row["replica_id"])
            if row.get("state") == REPLICA_DRAINING:
                # Drain was in flight when the old controller died:
                # finish the job (graceful stop + kill + row GC) instead
                # of leaking a zombie replica. Not a restart, not a
                # replacement — just a resumed retirement.
                if handle is not None:
                    stale = _ReplicaInfo(handle, row["version"])
                    stale.replica_id = row["replica_id"]
                    stale.state = REPLICA_DRAINING
                    st.draining.append(stale)
                    stale.drain_task = asyncio.ensure_future(
                        self._drain_and_stop(st, stale))
                else:
                    self._persist.delete_soon(key)
                continue
            if verdict in ("dead", "unhealthy"):
                self._replaced_total += 1
                try:
                    _replaced_counter().inc(tags={"Deployment": st.name})
                except Exception:  # noqa: BLE001
                    pass
                if handle is not None:
                    try:
                        ray_tpu.kill(handle)
                    except Exception:  # noqa: BLE001
                        pass
                self._persist.delete_soon(key)
                continue
            info = _ReplicaInfo(handle, row["version"])
            info.replica_id = row["replica_id"]
            info.target_slice = row.get("target_slice") or ""
            info.node_id = row.get("node_id")
            if verdict == "healthy":
                info.state = REPLICA_RUNNING
                info.ever_healthy = True
            st.replicas.append(info)
            by_rid[info.replica_id] = (info, row)
            self._reattached_total += 1
            try:
                _reattached_counter().inc(tags={"Deployment": st.name})
            except Exception:  # noqa: BLE001
                pass
        # Resume the rolling update from the persisted swap links:
        # replacement READY -> swap now (drain the old); replacement
        # still starting -> re-link so _wait_ready swaps when it lands.
        for _rid, (info, row) in list(by_rid.items()):
            old_rid = row.get("replaces")
            if not old_rid:
                continue
            old = by_rid.get(old_rid, (None, None))[0]
            if old is None:
                continue  # old already drained: this replica owns the slot
            if info.state == REPLICA_RUNNING:
                info.replaces = None
                self._begin_drain(st, old, "rolling update (resumed)")
            else:
                info.replaces = old
                old.being_replaced = True
        for info, _row in by_rid.values():
            if info.state == REPLICA_STARTING and info in st.replicas:
                info.ready_task = asyncio.ensure_future(
                    self._wait_ready(st, info))
        st.list_version += 1

    async def _reattach_proxies(self):
        """Re-bind the persisted proxy actors (they are detached and
        restartable: still-live ones keep serving from stale routes; a
        restarted instance needs one ready() to re-listen)."""
        from ray_tpu._private import worker_api
        from ray_tpu._private.common import ACTOR_DEAD
        from ray_tpu.actor import ActorHandle
        core = worker_api.peek_core()
        if core is None or not self._proxy_rec:
            return
        for kind, rec in list(self._proxy_rec.items()):
            if not isinstance(rec, dict) or "actor_id" not in rec:
                continue
            try:
                info = await core.gcs.request(
                    "get_actor_info", {"actor_id": rec["actor_id"]})
            except Exception:  # noqa: BLE001
                info = None
            alive = info is not None and info.state != ACTOR_DEAD
            try:
                if kind == "http":
                    if alive:
                        self._proxy = ActorHandle._from_actor_info(info)
                    else:
                        self._proxy = None
                        await self._ensure_proxy_inner(rec["host"],
                                                       rec["port"])
                elif kind == "grpc":
                    if alive:
                        self._grpc_proxy = ActorHandle._from_actor_info(info)
                        self._grpc_host = rec["host"]
                        self._grpc_port = rec["port"]
                    else:
                        self._grpc_proxy = None
                        await self._ensure_grpc_proxy_inner(rec["host"],
                                                            rec["port"])
            except Exception:  # noqa: BLE001 — proxy watch retries
                logger.exception("proxy reattach (%s) failed", kind)

    # ------------------------------------------------------------------
    # Boot: listeners + recovery + reconcile loop, exactly once
    # ------------------------------------------------------------------
    async def _ensure_loops(self):
        if self._boot_task is None:
            self._boot_task = asyncio.ensure_future(self._boot())
        await asyncio.shield(self._boot_task)

    async def _boot(self):
        self._wake = asyncio.Event()
        loop = asyncio.get_running_loop()
        wake = self._wake

        def _notice():
            loop.call_soon_threadsafe(wake.set)

        try:
            from ray_tpu._private import worker_api
            worker_api.add_drain_event_listener(_notice)
        except Exception:  # noqa: BLE001 — no core (unit tests)
            pass
        try:
            from ray_tpu.util import metrics
            metrics.start_loop_lag_probe_once("serve_controller")
        except Exception:  # noqa: BLE001 — lag probe is best-effort
            pass
        try:
            await self._recover()
        except Exception:  # noqa: BLE001 — recovery must not wedge boot
            logger.exception("serve controller recovery failed; "
                             "continuing from target state only")
        self._reconcile_task = asyncio.ensure_future(self._reconcile_loop())

    # ------------------------------------------------------------------
    # Write-ahead persistence helpers
    # ------------------------------------------------------------------
    def _target_record(self, st: _DeploymentState) -> dict:
        return persistence.target_record(st.app_name, st.name, st.blob,
                                         st.config, st.version,
                                         st.target_num)

    def _replica_row(self, st: _DeploymentState, info: _ReplicaInfo) -> dict:
        return persistence.replica_record(
            st.app_name, st.name, info.replica_id, info.handle._actor_id,
            info.version, info.state, node_id=info.node_id,
            target_slice=info.target_slice,
            replaces=info.replaces.replica_id
            if info.replaces is not None else None)

    async def _persist_replica_row(self, st: _DeploymentState,
                                   info: _ReplicaInfo,
                                   row: Optional[dict] = None):
        await self._persist.put(
            persistence.replica_key(st.app_name, st.name, info.replica_id),
            row if row is not None else self._replica_row(st, info))

    def _persist_replica_row_soon(self, st, info):
        try:
            asyncio.ensure_future(self._persist_replica_row(st, info))
        except RuntimeError:  # no loop (sync unit tests)
            pass

    async def _set_target(self, st: _DeploymentState, n: int, reason: str):
        """The ONE scale path: write-ahead the new target, then apply.
        (scripts/check_serve_persistence.py forbids raw target_num
        assignments elsewhere.)"""
        if n == st.target_num:
            return
        rec = self._target_record(st)
        rec["target_num"] = int(n)
        await self._persist.put(
            persistence.target_key(st.app_name, st.name), rec)
        logger.info("scale %s: %d -> %d (%s)", st.name, st.target_num, n,
                    reason)
        st.target_num = int(n)

    # ------------------------------------------------------------------
    # Deployment API
    # ------------------------------------------------------------------
    async def deploy_app(self, app_name: str, deployments: List[dict],
                         route_prefix: Optional[str], ingress: str):
        """deployments: [{name, blob, config, version}]"""
        await self._ensure_loops()
        async with self._api_lock:
            return await self._deploy_app_locked(
                app_name, deployments, route_prefix, ingress)

    async def _deploy_app_locked(self, app_name, deployments, route_prefix,
                                 ingress):
        # Write-ahead, app-atomic FIRST: one snapshot blob carrying every
        # deployment's target record + the route binding lands in a
        # single KV put before anything else. A crash between the per-
        # deployment records below can no longer recover a cross-
        # deployment version mix — _load_state reconciles stragglers
        # against the snapshot.
        incoming: Dict[tuple, dict] = {}
        for d in deployments:
            # ONE record per deployment, persisted then applied: the KV
            # copy and the in-memory state can never diverge field-wise.
            rec = persistence.target_record(
                app_name, d["name"], d["blob"], d["config"], d["version"],
                d["config"].num_replicas)
            incoming[(app_name, d["name"])] = rec
        await self._persist.put(
            persistence.app_key(app_name),
            persistence.app_snapshot_record(
                app_name, list(incoming.values()), route_prefix, ingress))
        for (_, name), rec in incoming.items():
            await self._persist.put(
                persistence.target_key(app_name, name), rec)
        if route_prefix is not None:
            routes = dict(self._routes)
            routes[route_prefix] = (app_name, ingress)
            await self._persist.put(persistence.ROUTES_KEY,
                                    {"routes": routes})
        for key, rec in incoming.items():
            cur = self._deployments.get(key)
            if cur is None:
                cur = _DeploymentState(rec["app"], rec["name"],
                                       rec["blob"], rec["config"],
                                       rec["version"])
                self._deployments[key] = cur
            self._apply_target_record(cur, rec)
        # Remove deployments no longer in the app.
        for key in [k for k in self._deployments
                    if k[0] == app_name and k not in incoming]:
            await self._remove_deployment(key)
        if route_prefix is not None:
            self._routes[route_prefix] = (app_name, ingress)
        await self._reconcile_once()
        return True

    async def delete_app(self, app_name: str):
        await self._ensure_loops()
        async with self._api_lock:
            # Snapshot first: a crash mid-delete must recover to "app
            # being removed", never resurrect deployments from a stale
            # snapshot after their target records are gone.
            await self._persist.delete(persistence.app_key(app_name))
            routes = {r: v for r, v in self._routes.items()
                      if v[0] != app_name}
            await self._persist.put(persistence.ROUTES_KEY,
                                    {"routes": routes})
            for key in [k for k in self._deployments if k[0] == app_name]:
                await self._remove_deployment(key)
            self._routes = routes
            return True

    async def _remove_deployment(self, key):
        st = self._deployments.get(key)
        if st is None:
            return
        # Write-ahead delete of the TARGET record first: a crash
        # mid-removal recovers to "deleted". The registry rows stay
        # until each replica is actually stopped — recovery finds
        # target-less rows and garbage-collects the survivors instead
        # of leaking them.
        await self._persist.delete(persistence.target_key(*key))
        self._deployments.pop(key, None)
        for r in list(st.replicas):
            if r.ready_task is not None:
                r.ready_task.cancel()
            await self._stop_replica(st, r.handle)
        st.replicas.clear()
        await self._persist.delete_prefix(
            f"replica/{key[0]}/{key[1]}/".encode())
        # Already-DRAINING replicas finish through their own drain tasks.

    # Idle linger before a drained replica dies: covers the router
    # routable-set cache window (Router.REFRESH_S) plus wire latency, so
    # late-routed requests bounce (re-route) instead of dying with the
    # actor. Only applied to live drains (rolling update / scale-down /
    # node drain) — app deletion kills without it.
    DRAIN_LINGER_S = 1.3

    async def _stop_replica(self, st, rep, linger_s: float = 0.0):
        timeout = st.config.graceful_shutdown_timeout_s
        try:
            await asyncio.wait_for(
                rep.drain.remote(timeout, linger_s).future(),
                timeout=timeout + linger_s + 2)
        except Exception:
            pass
        try:
            ray_tpu.kill(rep)
        except Exception:
            pass

    # ------------------------------------------------------------------
    # Replica lifecycle
    # ------------------------------------------------------------------
    async def _start_replica(self, st: _DeploymentState,
                             replaces: Optional[_ReplicaInfo] = None):
        if self._deployments.get((st.app_name, st.name)) is not st:
            # A reconcile pass parked across an await while delete_app
            # removed the deployment: starting a replica for the
            # orphaned state would leak a detached actor nobody tracks.
            return None
        from ray_tpu.serve.replica import ReplicaActor
        cfg = st.config
        opts = dict(cfg.ray_actor_options)
        opts.setdefault("num_cpus", 0.1)
        # Detached: replicas must survive their owner (this controller
        # worker) dying — the controller reattaches them on recovery;
        # lifecycle is explicit (drain/kill), never owner cleanup.
        opts.setdefault("lifetime", "detached")
        # Reserved namespace = sweep identity: recovery's orphan sweep
        # may only ever consider actors born here (forced, not
        # defaulted — an opt-out would silently leak create-before-
        # persist orphans).
        opts["namespace"] = SERVE_ACTOR_NAMESPACE
        # Admission control lives in the replica (bounded queue + shed):
        # the actor's concurrency cap must sit ABOVE max_ongoing + queue
        # so queued requests reach the replica's gate — and control
        # methods (health, drain, metrics) never starve behind a full
        # request queue.
        queued = (cfg.max_queued_requests if cfg.max_queued_requests >= 0
                  else 2048)
        opts.setdefault("max_concurrency",
                        cfg.max_ongoing_requests + queued + 32)
        target_slice = ""
        if cfg.slice_spread and "scheduling_strategy" not in opts:
            strat, target_slice = await self._slice_spread_strategy(st)
            if strat is not None:
                opts["scheduling_strategy"] = strat
        cls = ray_tpu.remote(**opts)(ReplicaActor)
        limits = {"deployment": st.name,
                  "max_ongoing": cfg.max_ongoing_requests,
                  "max_queued": cfg.max_queued_requests,
                  "request_replay": cfg.request_replay,
                  # Replica-side SLO accounting (slow-request counter)
                  # needs the latency target; 0 disables.
                  "slo_latency_target_s":
                      cfg.slo_config.target_p99_s
                      if cfg.slo_config is not None else 0.0}
        rep = cls.remote(st.blob, cfg.user_config, limits)
        info = _ReplicaInfo(rep, st.version)
        info.replaces = replaces
        info.target_slice = target_slice
        self._known_actor_ids.add(rep._actor_id)  # never orphan-swept
        # Registry row BEFORE the replica set publishes: recovery must
        # know about every replica routers might have seen. If the
        # persist fails, the just-created detached actor must not leak
        # (no row, no routing entry, no owner to reap it) — kill it and
        # let the next reconcile pass retry the whole start.
        try:
            await self._persist_replica_row(st, info)
        except BaseException:
            try:
                ray_tpu.kill(rep)
            except Exception:  # noqa: BLE001
                pass
            raise
        st.replicas.append(info)
        st.list_version += 1
        info.ready_task = asyncio.ensure_future(self._wait_ready(st, info))
        return info

    async def _wait_ready(self, st: _DeploymentState, info: _ReplicaInfo):
        """Background readiness watcher: bounded, one per replica — a
        hung constructor stalls only its own watcher, never the
        reconcile loop (the health loop's startup grace reaps it)."""
        try:
            await asyncio.wait_for(
                info.handle.check_health.remote().future(),
                timeout=st.STARTUP_GRACE_S)
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        # Persist the swap outcome BEFORE publishing it: a crash right
        # here recovers a RUNNING replacement that owns its slot, and
        # the (still-registered) old replica drains as the stale-version
        # overshoot — never a restarted rollout.
        row = self._replica_row(st, info)
        row["state"] = REPLICA_RUNNING
        row["replaces"] = None
        try:
            await self._persist_replica_row(st, info, row)
        except Exception:  # noqa: BLE001 — persistence lags, serving wins
            logger.debug("replica row persist failed", exc_info=True)
        # READY + swap in ONE sync block (no await between them): the
        # routable set must never publish a version where both the old
        # replica and its replacement serve — a client that already saw
        # the new version could be routed back to the old one.
        info.ever_healthy = True
        if info.state == REPLICA_STARTING:
            info.state = REPLICA_RUNNING
            st.list_version += 1
        old, info.replaces = info.replaces, None
        if old is not None and old in st.replicas:
            # Replace-then-drain: the replacement serves before the old
            # replica retires (rolling, never big-bang).
            self._begin_drain(st, old, "rolling update")
        try:
            info.node_id = await self._actor_node(info.handle)
            self._persist_replica_row_soon(st, info)
        except Exception:  # noqa: BLE001 — placement info is best-effort
            pass

    def _begin_drain(self, st: _DeploymentState, r: _ReplicaInfo,
                     reason: str):
        """DRAINING: out of the routable set immediately; queued work is
        handed back to routers by the replica; in-flight finishes within
        graceful_shutdown_timeout_s; then the actor dies."""
        if r.state == REPLICA_DRAINING:
            return
        if r.ready_task is not None:
            r.ready_task.cancel()
        if r in st.replicas:
            st.replicas.remove(r)
        st.list_version += 1
        r.state = REPLICA_DRAINING
        st.draining.append(r)
        # The registry row stays (marked DRAINING) until the drain
        # COMPLETES: if this controller dies mid-drain, recovery finds
        # the row and finishes the kill instead of leaking a zombie
        # replica actor whose drain task died with us.
        self._persist_replica_row_soon(st, r)
        logger.info("draining replica %s of %s (%s)",
                    r.replica_id, st.name, reason)
        r.drain_task = asyncio.ensure_future(self._drain_and_stop(st, r))

    async def _drain_and_stop(self, st: _DeploymentState, r: _ReplicaInfo):
        await self._stop_replica(st, r.handle, linger_s=self.DRAIN_LINGER_S)
        if r in st.draining:
            st.draining.remove(r)
        # Registry GC only now that the actor is gone (see _begin_drain).
        self._persist.delete_soon(persistence.replica_key(
            st.app_name, st.name, r.replica_id))

    # ------------------------------------------------------------------
    # Reconciliation
    # ------------------------------------------------------------------
    async def _reconcile_once(self):
        async with self._reconcile_lock:
            await self._reconcile_locked()

    async def _reconcile_locked(self):
        for st in list(self._deployments.values()):
            # Rolling update: replace stale-version replicas one at a
            # time — new replica first, old drained once it's READY.
            if not any(r.replaces is not None for r in st.replicas):
                stale = next(
                    (r for r in st.replicas
                     if r.version != st.version and not r.being_replaced),
                    None)
                if stale is not None:
                    stale.being_replaced = True
                    await self._start_replica(st, replaces=stale)
            # Scale to target (replacement replicas don't fill a slot).
            # Warm the worker pools FIRST: every deficit path — initial
            # deploy, queue-policy upscale, SLO-burn upscale, gang
            # failover — funnels through here, and the replica actors'
            # time-to-READY is bounded by worker spawn.
            deficit = st.target_num - len(st.active())
            if deficit > 0:
                await self._prestart_for(st, deficit)
            while len(st.active()) < st.target_num:
                if await self._start_replica(st) is None:
                    break  # deployment deleted mid-pass (orphan guard)
            while len(st.active()) > st.target_num:
                # Prefer retiring stale-version replicas (a recovered
                # mid-swap rollout drains the OLD side), then replicas
                # that never served, then the newest — oldest replicas
                # are the proven ones.
                victims = sorted(
                    (r for r in st.active() if not r.being_replaced),
                    key=lambda r: (r.version == st.version,
                                   r.state == REPLICA_RUNNING, -r.started))
                if not victims:
                    break
                self._begin_drain(st, victims[0], "scale down")

    async def _prestart_for(self, st: _DeploymentState, deficit: int):
        """Send the GCS a prestart hint for `deficit` replica workers
        (throttled: the reconcile loop re-enters every ~0.5s while the
        replicas start — re-hinting the same deficit would just churn)."""
        now = time.time()
        if deficit <= st.last_prestart_n and now - st.last_prestart < 5.0:
            return
        st.last_prestart, st.last_prestart_n = now, deficit
        try:
            from ray_tpu._private import worker_api
            await worker_api.prestart_workers_async(
                worker_api.get_core(), deficit,
                (st.config.ray_actor_options or {}).get("runtime_env"))
        except Exception:  # noqa: BLE001 — a hint is best-effort
            logger.debug("prestart hint failed", exc_info=True)

    async def _reconcile_loop(self):
        while True:
            try:
                self._process_drain_notices()
                await self._reconcile_once()
                await self._health_check()
                await self._autoscale()
                await self._watch_proxies()
            except Exception:
                logger.exception("serve controller reconcile error")
            # Jittered so co-resident controllers/probes desynchronize;
            # the wake event short-circuits the sleep on drain notices.
            period = self.RECONCILE_PERIOD_S * random.uniform(0.7, 1.3)
            try:
                await asyncio.wait_for(self._wake.wait(), period)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()

    def _process_drain_notices(self):
        """Proactively drain replicas whose host got a drain/preemption
        notice (PR 1 two-phase drain, PR 4 gang drains): their queued
        work re-routes NOW instead of dying with the node at the
        deadline. The reconcile pass tops the count back up — on a
        healthy domain, thanks to slice spread."""
        try:
            from ray_tpu._private import worker_api
            events = worker_api.drain_events()
        except Exception:  # noqa: BLE001
            return
        new = events[self._drain_seen:]
        self._drain_seen = len(events)
        if not new:
            return
        draining_nodes = set()
        for ev in new:
            ids = ev.get("node_ids") or (
                [ev["node_id"]] if ev.get("node_id") is not None else [])
            draining_nodes.update(ids)
        if not draining_nodes:
            return
        for st in list(self._deployments.values()):
            for r in list(st.replicas):
                if r.node_id is not None and r.node_id in draining_nodes:
                    self._begin_drain(st, r, "node drain notice")

    async def _health_check(self):
        from ray_tpu import exceptions as exc
        now = time.monotonic()
        for st in list(self._deployments.values()):
            if now < st.next_health_check:
                continue
            st.next_health_check = now + (
                st.config.health_check_period_s * random.uniform(0.75, 1.25))

            # Multiplex resident-model poll: deployments with an
            # autoscaler/SLO already get_metrics every _autoscale pass
            # (which updates resident sets) — only poll here for the
            # rest, CONCURRENTLY with the health probe so a wedged
            # replica costs one 5 s window, not two.
            poll_resident = (st.config.autoscaling_config is None
                             and st.slo is None)

            async def check(r, st=st, poll_resident=poll_resident):
                res_task = asyncio.ensure_future(
                    self._poll_resident(st, r)) if poll_resident else None
                try:
                    await asyncio.wait_for(
                        r.handle.check_health.remote().future(), timeout=5)
                    verdict = True
                except exc.ActorDiedError:
                    verdict = "dead"   # definitive: GCS marked it dead
                except Exception:
                    verdict = False    # slow/unreachable: maybe starting
                if res_task is not None:
                    await res_task
                return verdict
            # Probe all replicas concurrently: serial checks would make one
            # slow/dead replica delay the whole reconcile pass by its
            # timeout multiplied by the replica count.
            oks = await asyncio.gather(*[check(r) for r in st.replicas])
            for i, r in reversed(list(enumerate(st.replicas))):
                ok = oks[i]
                if ok is True:
                    r.ever_healthy = True
                    if r.state == REPLICA_STARTING:
                        r.state = REPLICA_RUNNING
                        st.list_version += 1
                        self._persist_replica_row_soon(st, r)
                    continue
                # A replica that has never come up yet may simply still be
                # starting (worker spawn under load): give it a grace
                # period before declaring it dead — unless its death is
                # definitive (a replica can crash before its first health
                # check ever succeeds; waiting out the grace would stall
                # recovery for a minute).
                if (ok is False and not r.ever_healthy
                        and now - r.started < st.STARTUP_GRACE_S):
                    continue
                self._drop_dead_replica(st, r)
        # reconcile_once (caller loop) will top the count back up

    def _update_resident(self, st: _DeploymentState, r: _ReplicaInfo,
                         m: dict) -> None:
        """Fold one get_metrics result's resident-model set into routing
        state; a change bumps list_version so routers re-pull the table
        (which carries the sets)."""
        resident = frozenset(m.get("resident_models") or ())
        if resident != r.resident_models:
            r.resident_models = resident
            st.list_version += 1

    async def _poll_resident(self, st: _DeploymentState, r: _ReplicaInfo):
        try:
            m = await asyncio.wait_for(
                r.handle.get_metrics.remote().future(), timeout=5)
            self._update_resident(st, r, m)
        except Exception:  # noqa: BLE001 — routing hint only
            pass

    def _drop_dead_replica(self, st: _DeploymentState, r: _ReplicaInfo):
        if r in st.replicas:
            st.replicas.remove(r)
        self._persist.delete_soon(persistence.replica_key(
            st.app_name, st.name, r.replica_id))
        st.list_version += 1
        if r.ready_task is not None:
            r.ready_task.cancel()
        # Untangle rolling-update links so the swap machinery retries.
        if r.replaces is not None:
            r.replaces.being_replaced = False
            r.replaces = None
        for other in st.replicas:
            if other.replaces is r:
                other.replaces = None
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass

    async def _proxy_slo_rows(self) -> Dict[str, dict]:
        """Per-deployment proxy-side queue counters from the GCS tsdb.

        Proxies count dispatched requests and queue-wait SLO misses into
        their process registries; the metrics frames carry them to the
        tsdb, which merges across proxies. Folding the latest cumulative
        values into DeploymentSLO as ONE pseudo-replica per deployment
        closes the PR 7 blind spot: burn now fires on proxy-only
        queueing delay (stalled proxy loop, controller round trips)
        that replica-side counters can never see."""
        if not any(st.slo is not None for st in self._deployments.values()):
            return {}
        try:
            from ray_tpu._private import worker_api
            core = worker_api.get_core()
            res = await asyncio.wait_for(core.gcs.request(
                "metrics_query", {"queries": [
                    {"name": "ray_tpu_serve_proxy_requests_total",
                     "fold": "latest"},
                    {"name": "ray_tpu_serve_proxy_queue_slow_total",
                     "fold": "latest"},
                ]}), timeout=5)
        except Exception:  # noqa: BLE001 — telemetry gaps never stall
            return {}      # autoscaling; the next pass re-baselines
        folds: list = [{}, {}]
        for series_list, dest in zip(res, folds):
            for s in series_list:
                dep = s["tags"].get("Deployment", "")
                if dep and s["points"]:
                    dest[dep] = dest.get(dep, 0.0) + s["points"][-1][1]
        totals, slows = folds
        return {dep: {"completed": total, "slow": slows.get(dep, 0.0)}
                for dep, total in totals.items()}

    async def _autoscale(self):
        now = time.monotonic()
        proxy_rows = await self._proxy_slo_rows()
        for st in list(self._deployments.values()):
            asc = st.config.autoscaling_config
            if (asc is None and st.slo is None) or not st.replicas:
                continue

            async def metrics(r):
                try:
                    return await asyncio.wait_for(
                        r.handle.get_metrics.remote().future(), timeout=5)
                except Exception:
                    return None
            results = await asyncio.gather(
                *[metrics(r) for r in st.replicas])
            polled = {r.replica_id: m
                      for r, m in zip(st.replicas, results) if m}
            for r, m in zip(st.replicas, results):
                if m:   # this poll doubles as the resident-model poll
                    self._update_resident(st, r, m)
            # SLO burn: evaluated every pass (gauges/violations export
            # even without autoscaling); with autoscaling it scales UP on
            # sustained burn — latency pressure fires before the bounded
            # queue fills, so burn-driven capacity lands before a single
            # request is shed.
            verdict = None
            if st.slo is not None and polled:
                rows = dict(polled)
                prow = proxy_rows.get(st.name)
                if prow:
                    # The proxy plane as one pseudo-replica: restart
                    # clamping and vanish cleanup come from the same
                    # per-reporter machinery replicas use.
                    rows[f"proxy::{st.name}"] = prow
                st.slo.ingest(rows)
                verdict = st.slo.evaluate()
                if (verdict["violating"] and asc is not None
                        and st.target_num < asc.max_replicas
                        and now - st.last_slo_scale
                        >= st.config.slo_config.upscale_cooldown_s):
                    await self._set_target(
                        st, st.target_num + 1,
                        f"SLO burn fast={verdict['fast']:.1f} "
                        f"slow={verdict['slow']:.1f}")
                    st.last_slo_scale = now
                    st.last_scale_change = now
                    continue  # burn owns this tick: no queue downscale
                if verdict["violating"]:
                    # Still burning (at max, or cooling down): never let
                    # the queue-depth policy scale DOWN a burning
                    # deployment.
                    st.last_scale_change = now
                    continue
            if asc is None:
                continue
            # Queued requests count toward pressure: with replica-side
            # admission queues, "ongoing" alone under-reports load.
            total = sum(m["ongoing"] + m.get("queued", 0)
                        for m in polled.values())
            desired = asc.decide(len(st.active()), total)
            if st.slo is not None and desired < st.target_num:
                # Burn-driven DOWNSCALE: with an SLO configured, capacity
                # only shrinks when the error budget has not burned for a
                # full slow window AND the queue policy agrees — and then
                # by ONE replica per its own cooldown, so a recovery
                # blip never cliffs the fleet.
                cfg = st.config.slo_config
                idle_s = verdict["idle_s"] if verdict else 0.0
                if (idle_s >= cfg.slow_window_s
                        and now - st.last_slo_downscale
                        >= cfg.downscale_cooldown_s):
                    await self._set_target(
                        st, max(asc.min_replicas, st.target_num - 1),
                        f"SLO idle {idle_s:.0f}s, queue wants {desired}")
                    st.last_slo_downscale = now
                    st.last_scale_change = now
                continue
            delay = (asc.upscale_delay_s if desired > st.target_num
                     else asc.downscale_delay_s)
            if desired != st.target_num:
                if now - st.last_scale_change >= delay:
                    await self._set_target(
                        st, desired, f"queue autoscale ongoing={total:.1f}")
                    st.last_scale_change = now
            else:
                st.last_scale_change = now

    # ------------------------------------------------------------------
    # Slice fault-domain spread
    # ------------------------------------------------------------------
    async def _slice_domains(self):
        now = time.monotonic()
        if now - self._nodes_ts < 2.0:
            return self._domains
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        infos = await core.gcs.request("get_all_nodes", {})
        domains: Dict[str, list] = {}
        node_slice: Dict[Any, str] = {}
        for n in infos:
            sid = getattr(n, "slice_id", "")
            if not sid:
                continue
            node_slice[n.node_id] = sid
            if n.alive and not getattr(n, "draining", False):
                domains.setdefault(sid, []).append(n)
        self._domains = domains
        self._node_slice = node_slice
        self._nodes_ts = now
        return domains

    async def _slice_spread_strategy(self, st: _DeploymentState):
        """Anti-affinity across TPU-slice fault domains: pick the domain
        hosting the fewest of this deployment's replicas, soft node
        affinity into it — one slice preemption can then never take the
        whole deployment."""
        try:
            domains = await self._slice_domains()
        except Exception:  # noqa: BLE001 — placement hint is best-effort
            return None, ""
        if len(domains) < 2:
            return None, ""
        counts = {s: 0 for s in domains}
        for r in st.replicas:
            sid = r.target_slice or self._node_slice.get(r.node_id, "")
            if sid in counts:
                counts[sid] += 1
        target = min(sorted(counts), key=lambda s: counts[s])
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        node = domains[target][0]
        return NodeAffinitySchedulingStrategy(node.node_id, soft=True), target

    async def _actor_node(self, handle):
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        info = await core.gcs.request(
            "get_actor_info", {"actor_id": handle._actor_id})
        return getattr(info, "node_id", None)

    # ------------------------------------------------------------------
    # Queries
    # ------------------------------------------------------------------
    async def get_replicas(self, app_name: str, deployment_name: str):
        await self._ensure_loops()
        st = self._deployments.get((app_name, deployment_name))
        if st is None:
            return (0, [])
        return (st.list_version, [r.handle for r in st.replicas])

    async def get_routing(self, app_name: str, deployment_name: str):
        """Routable replica set + the routing-relevant config bits.

        RUNNING replicas only — except cold start (none RUNNING yet),
        where STARTING replicas are offered so requests queue on a
        booting replica instead of failing."""
        await self._ensure_loops()
        st = self._deployments.get((app_name, deployment_name))
        if st is None:
            return {"version": 0, "replicas": [], "config": {}}
        routable = [r for r in st.replicas if r.state == REPLICA_RUNNING]
        if not routable:
            routable = list(st.replicas)
        return {
            "version": st.list_version,
            "replicas": [(r.replica_id, r.handle) for r in routable],
            # Multiplexing: per-replica resident-model sets (polled with
            # health) — handles route model-tagged requests to replicas
            # that already hold the model.
            "resident": {r.replica_id: sorted(r.resident_models)
                         for r in routable if r.resident_models},
            "config": {
                "deployment": st.name,
                "request_replay": st.config.request_replay,
                "request_timeout_s": st.config.request_timeout_s,
            },
        }

    async def get_route_table(self):
        await self._ensure_loops()
        return dict(self._routes)

    async def get_slo_queue_targets(self):
        """Deployment -> SLO latency target (s), for the proxies' queue-
        wait accounting. Only SLO-configured deployments appear; a proxy
        never classifies queue wait for deployments with no target."""
        return {st.name: st.config.slo_config.target_p99_s
                for st in self._deployments.values()
                if st.config.slo_config is not None}

    async def get_proxy_actor_id(self):
        """The detached HTTP proxy's actor id (tests / tooling build a
        direct handle from it via get_actor_info)."""
        rec = self._proxy_rec.get("http") or {}
        return rec.get("actor_id")

    async def status(self):
        await self._ensure_loops()
        out = {}
        for (app, name), st in self._deployments.items():
            row = {
                "target": st.target_num,
                "running": len(st.replicas),
                "ready": sum(1 for r in st.replicas
                             if r.state == REPLICA_RUNNING),
                "draining": len(st.draining),
                "version": st.version,
            }
            if st.slo is not None:
                row["slo"] = {
                    "burn_fast": round(st.slo.burn_fast, 3),
                    "burn_slow": round(st.slo.burn_slow, 3),
                    "violating": st.slo.violating,
                    "violations": st.slo.violations,
                }
            out.setdefault(app, {})[name] = row
        return out

    async def ping(self):
        """Cheap liveness/identity probe: answers DURING recovery (it
        kicks boot instead of awaiting it) so proxies can re-anchor
        their healthz grace on recovery progress."""
        if self._boot_task is None:
            self._boot_task = asyncio.ensure_future(self._boot())
        return {"pid": os.getpid(),
                "recovering": not self._boot_task.done(),
                "recovered": self._recover_t0 > 0}

    async def recovery_info(self):
        await self._ensure_loops()
        return {"recoveries": self._recoveries_cum,
                "recovered": self._recover_t0 > 0,
                "reattached": self._reattached_total,
                "replaced": self._replaced_total,
                "pid": os.getpid()}

    # ------------------------------------------------------------------
    # Proxies
    # ------------------------------------------------------------------
    async def ensure_proxy(self, host: str, port: int):
        await self._ensure_loops()
        return await self._ensure_proxy_inner(host, port)

    async def _ensure_proxy_inner(self, host: str, port: int):
        # Split from ensure_proxy: recovery (inside _boot) re-creates a
        # dead proxy through HERE — the public method's _ensure_loops
        # would await the very boot task recovery runs in (deadlock).
        # _proxy_lock serializes the PROXIES_KEY read-modify-write with
        # the grpc path: an interleaved copy would drop the other
        # binding from the KV, and the next recovery would then
        # orphan-sweep a healthy listening proxy.
        async with self._proxy_lock:
            if self._proxy is None:
                from ray_tpu.serve.proxy import ProxyActor
                # Detached + restartable: the ingress must outlive both
                # this controller worker and its own crashes (the proxy
                # watch re-arms the listener after a restart).
                cls = ray_tpu.remote(
                    num_cpus=0.1, max_restarts=-1, lifetime="detached",
                    namespace=SERVE_ACTOR_NAMESPACE)(ProxyActor)
                proxy = cls.remote(host, port)
                self._known_actor_ids.add(proxy._actor_id)
                await proxy.ready.remote()
                rec = dict(self._proxy_rec)
                rec["http"] = {"actor_id": proxy._actor_id, "host": host,
                               "port": port}
                await self._persist.put(persistence.PROXIES_KEY, rec)
                self._proxy_rec = rec
                self._proxy = proxy
        return True

    async def ensure_grpc_proxy(self, host: str, port: int) -> int:
        """Start the binary-RPC ingress (reference: gRPCProxy); returns the
        bound port."""
        await self._ensure_loops()
        return await self._ensure_grpc_proxy_inner(host, port)

    async def _ensure_grpc_proxy_inner(self, host: str, port: int) -> int:
        # Split for the same boot-reentrancy reason (and under the same
        # PROXIES_KEY serialization) as _ensure_proxy_inner.
        async with self._proxy_lock:
            if getattr(self, "_grpc_proxy", None) is None:
                from ray_tpu.serve.grpc_proxy import GrpcProxyActor
                cls = ray_tpu.remote(
                    num_cpus=0.1, max_restarts=-1, lifetime="detached",
                    namespace=SERVE_ACTOR_NAMESPACE)(GrpcProxyActor)
                actor = cls.remote(host, port)
                self._known_actor_ids.add(actor._actor_id)
                try:
                    self._grpc_port = await actor.ready.remote()
                except Exception:
                    # Failed startup (e.g. port in use) stays retryable.
                    try:
                        ray_tpu.kill(actor)
                    except Exception:
                        pass
                    raise
                self._grpc_host = host
                self._grpc_proxy = actor
                rec = dict(self._proxy_rec)
                # Persist the BOUND port: a recovered controller
                # recreating a dead ingress must rebind where clients
                # already point.
                rec["grpc"] = {"actor_id": actor._actor_id, "host": host,
                               "port": self._grpc_port}
                await self._persist.put(persistence.PROXIES_KEY, rec)
                self._proxy_rec = rec
        return self._grpc_port

    async def _watch_proxies(self):
        """Proxy autonomy, controller side: proxies are restartable
        detached actors, but a restarted instance listens again only
        when someone calls ready() — this throttled watch is that
        someone. It also retries a recreation that failed during
        recovery (a persisted binding with no live handle). The probe
        runs as a background task: a parked ready() on a mid-restart
        proxy must not stall the reconcile/health cadence."""
        now = time.monotonic()
        if now < self._next_proxy_watch:
            return
        if self._proxy_watch_task is not None \
                and not self._proxy_watch_task.done():
            return  # previous probe still in flight (parked call)
        self._next_proxy_watch = now + self.PROXY_WATCH_PERIOD_S
        self._proxy_watch_task = asyncio.ensure_future(
            self._watch_proxies_inner())

    async def _watch_proxies_inner(self):
        for kind in ("http", "grpc"):
            actor = self._proxy if kind == "http" \
                else getattr(self, "_grpc_proxy", None)
            if actor is None:
                # Persisted binding with no live handle: the recovery
                # recreation failed (port briefly held, GCS hiccup) —
                # keep retrying here until ingress is back.
                rec = self._proxy_rec.get(kind)
                if not isinstance(rec, dict) or "host" not in rec:
                    continue
                try:
                    if kind == "http":
                        await self._ensure_proxy_inner(rec["host"],
                                                       rec["port"])
                    else:
                        await self._ensure_grpc_proxy_inner(rec["host"],
                                                            rec["port"])
                except Exception:  # noqa: BLE001 — next pass retries
                    logger.debug("proxy recreate retry failed",
                                 exc_info=True)
                continue
            try:
                await asyncio.wait_for(actor.ready.remote().future(),
                                       timeout=5)
            except Exception:  # noqa: BLE001 — restarting: next pass
                logger.debug("proxy watch ready() failed", exc_info=True)

    def get_grpc_address(self) -> str:
        if getattr(self, "_grpc_proxy", None) is None:
            raise RuntimeError("binary-RPC ingress not started; "
                               "serve.start(grpc_proxy=True)")
        return f"{self._grpc_host}:{self._grpc_port}"

    async def shutdown(self):
        await self._ensure_loops()
        async with self._api_lock:
            return await self._shutdown_locked()

    async def _shutdown_locked(self):
        for key in list(self._deployments):
            await self._remove_deployment(key)
        # Clear ALL serve state (routes, proxies, recovery meta): a
        # shut-down serve instance must not be "recovered" by the next
        # controller this cluster starts — and the proxy watch must not
        # resurrect the proxies we kill below.
        self._proxy_rec = {}
        try:
            await self._persist.delete_prefix(b"")
        except Exception:  # noqa: BLE001
            logger.debug("serve state clear failed", exc_info=True)
        if getattr(self, "_grpc_proxy", None) is not None:
            try:
                ray_tpu.kill(self._grpc_proxy)
            except Exception:
                pass
            self._grpc_proxy = None
        if self._proxy is not None:
            try:
                ray_tpu.kill(self._proxy)
            except Exception:
                pass
            self._proxy = None
        return True
