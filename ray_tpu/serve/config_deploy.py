"""Declarative Serve deployment: config files + import paths.

Reference parity: the Serve CLI (`serve run module:app`,
`serve deploy config.yaml`, `serve status` — python/ray/serve/scripts.py)
and the multi-application config schema
(serve/schema.py ServeDeploySchema, trimmed to the fields this stack
uses):

    proxy: true
    applications:
      - name: app1
        route_prefix: /app1
        import_path: my_module:app
        deployments:              # per-deployment overrides (optional)
          - name: Model
            num_replicas: 2
            max_ongoing_requests: 8

`import_path` is "module:attr" where attr is an Application (the result
of `.bind()`) or a Deployment (bound with no args).
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from ray_tpu.serve.deployment import Application, Deployment


def _copy_graph(app: Application) -> Application:
    """Fresh Application nodes for the whole graph: the imported object
    lives on a sys.modules-cached module, so overrides applied in place
    would leak into every later deploy of the same import_path."""
    def visit(a: Application) -> Application:
        new_args = tuple(visit(x) if isinstance(x, Application) else x
                         for x in a.init_args)
        new_kwargs = {k: (visit(v) if isinstance(v, Application) else v)
                      for k, v in a.init_kwargs.items()}
        return Application(deployment=a.deployment, init_args=new_args,
                           init_kwargs=new_kwargs)
    return visit(app)


def import_application(import_path: str) -> Application:
    mod_name, _, attr = import_path.partition(":")
    if not attr:
        raise ValueError(
            f"import path {import_path!r} must be 'module:attribute'")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if isinstance(obj, Deployment):
        obj = obj.bind()
    if not isinstance(obj, Application):
        raise TypeError(f"{import_path!r} is {type(obj).__name__}, "
                        f"expected an Application (call .bind()) or "
                        f"Deployment")
    return _copy_graph(obj)


def _apply_overrides(app: Application,
                     overrides: List[Dict[str, Any]]) -> Application:
    """Per-deployment option overrides by deployment name (reference:
    schema-driven option merging in serve/_private/deploy_utils.py)."""
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"}
               for o in (overrides or [])}
    if not by_name:
        return app
    flat = app.flatten()
    unknown = set(by_name) - set(flat)
    if unknown:
        raise ValueError(f"config overrides unknown deployments: "
                         f"{sorted(unknown)}; app has {sorted(flat)}")
    for name, opts in by_name.items():
        target = flat[name]
        target.deployment = target.deployment.options(**opts)
    return app


def load_serve_config(path_or_dict) -> Dict[str, Any]:
    if isinstance(path_or_dict, dict):
        import copy
        # deep copy: validation fills defaults into the nested app dicts
        # and must not mutate the caller's config
        cfg = copy.deepcopy(path_or_dict)
    else:
        import yaml
        with open(path_or_dict) as f:
            cfg = yaml.safe_load(f)
    apps = cfg.get("applications")
    if not apps:
        raise ValueError("serve config needs a non-empty 'applications' "
                         "list")
    seen = set()
    for a in apps:
        if "import_path" not in a:
            raise ValueError("every application needs an import_path")
        name = a.setdefault("name", "default")
        if name in seen:
            raise ValueError(f"duplicate application name {name!r}")
        seen.add(name)
        a.setdefault("route_prefix", "/" if len(apps) == 1
                     else f"/{name}")
    return cfg


def deploy_config(path_or_dict, *, _blocking: bool = True) -> List[str]:
    """`serve deploy`: bring up every application in the config. Returns
    the deployed application names."""
    from ray_tpu import serve

    cfg = load_serve_config(path_or_dict)
    serve.start(proxy=bool(cfg.get("proxy", True)),
                http_options=cfg.get("http_options"))
    deployed = []
    for a in cfg["applications"]:
        app = import_application(a["import_path"])
        app = _apply_overrides(app, a.get("deployments"))
        serve.run(app, name=a["name"], route_prefix=a["route_prefix"],
                  _blocking_until_ready=_blocking)
        deployed.append(a["name"])
    return deployed


def run_import_path(import_path: str, *, name: str = "default",
                    route_prefix: str = "/", proxy: bool = True):
    """`serve run module:app` — single-application convenience."""
    from ray_tpu import serve

    serve.start(proxy=proxy)
    app = import_application(import_path)
    return serve.run(app, name=name, route_prefix=route_prefix)
