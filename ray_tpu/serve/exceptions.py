"""Typed serve request-path errors.

Reference parity: python/ray/serve/exceptions.py (BackPressureError,
RequestCancelledError) and the gRPC status-code mapping in
_private/proxy.py. Every error a request can hit on the serve path is
typed so callers (and the HTTP/binary proxies) can distinguish "shed it"
from "replica died" from "deadline passed" — the proxies map `code` to
HTTP 503/504 and the binary ingress ships the exception itself (the
gRPC RESOURCE_EXHAUSTED analogue rides the `code` attribute).
"""

from __future__ import annotations

from ray_tpu.exceptions import RayTpuError


class ServeError(RayTpuError):
    """Base class for serve request-path errors."""

    #: gRPC-style status code surfaced by the binary ingress.
    code = "INTERNAL"
    #: HTTP status the proxy maps this error to.
    http_status = 500


class BackPressureError(ServeError):
    """The deployment's bounded queue is full: the request was shed
    (drop-newest) instead of queueing unboundedly. Retry later or scale
    up; the proxies surface this as HTTP 503 / RESOURCE_EXHAUSTED."""

    code = "RESOURCE_EXHAUSTED"
    http_status = 503

    def __init__(self, deployment: str = "", queued: int = 0,
                 limit: int = 0):
        self.deployment = deployment
        self.queued = queued
        self.limit = limit
        super().__init__(
            f"deployment {deployment!r} shed request: {queued} queued >= "
            f"max_queued_requests={limit}")

    def __reduce__(self):
        return (BackPressureError, (self.deployment, self.queued,
                                    self.limit))


class RequestTimeoutError(ServeError, TimeoutError):
    """The request's end-to-end deadline passed. Raised on the replica
    (the in-flight handler is cancelled so it stops burning TPU time) or
    router-side when the deadline expires during routing/replay."""

    code = "DEADLINE_EXCEEDED"
    http_status = 504

    def __init__(self, deployment: str = "", timeout_s: float = 0.0,
                 where: str = "replica"):
        self.deployment = deployment
        self.timeout_s = timeout_s
        self.where = where
        super().__init__(
            f"request to deployment {deployment!r} exceeded its "
            f"{timeout_s:.3g}s deadline ({where})")

    def __reduce__(self):
        return (RequestTimeoutError, (self.deployment, self.timeout_s,
                                      self.where))


class ReplicaDiedError(ServeError):
    """The replica executing this request died (crash, slice preemption)
    and the request is NOT replayable (`request_replay=False`): fail
    fast with the typed cause instead of hanging or silently re-running
    a possibly non-idempotent handler."""

    code = "UNAVAILABLE"
    http_status = 503

    def __init__(self, deployment: str = "", reason: str = "replica died"):
        self.deployment = deployment
        self.reason = reason
        super().__init__(
            f"replica of deployment {deployment!r} died mid-request "
            f"({reason}); set request_replay=True on the deployment to "
            f"re-route idempotent requests instead")

    def __reduce__(self):
        return (ReplicaDiedError, (self.deployment, self.reason))


class ReplicaDrainingError(ServeError):
    """Internal re-route signal: the replica is draining (scale-down,
    rolling update, node drain) and handed this still-QUEUED request
    back before it started executing. The router always replays these —
    a request that never started is replay-safe regardless of the
    deployment's request_replay setting. User code should never see
    this error; reaching a caller means every re-route attempt failed."""

    code = "UNAVAILABLE"
    http_status = 503

    def __init__(self, deployment: str = ""):
        self.deployment = deployment
        super().__init__(
            f"replica of deployment {deployment!r} is draining; request "
            f"handed back to the router")

    def __reduce__(self):
        return (ReplicaDrainingError, (self.deployment,))


def unwrap(err: BaseException) -> BaseException:
    """Peel the TaskError envelope off a replica-raised exception: actor
    methods surface application errors as TaskError(cause); the serve
    layer routes on the typed cause."""
    from ray_tpu.exceptions import TaskError
    if isinstance(err, TaskError) and err.cause is not None:
        return err.cause
    return err
