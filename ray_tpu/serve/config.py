"""Serve configuration schemas (reference: python/ray/serve/config.py,
serve/schema.py — dataclasses here instead of pydantic)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth-driven replica autoscaling
    (reference: serve/autoscaling_policy.py)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0

    def decide(self, num_replicas: int, total_ongoing: float) -> int:
        """Desired replica count from current load."""
        if num_replicas == 0:
            return self.min_replicas
        per = total_ongoing / num_replicas
        desired = num_replicas
        if per > self.target_ongoing_requests:
            import math
            desired = math.ceil(
                total_ongoing / self.target_ongoing_requests)
        elif per < self.target_ongoing_requests / 2:
            import math
            desired = max(1, math.ceil(
                total_ongoing / self.target_ongoing_requests))
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class SLOConfig:
    """Per-deployment service-level objective, evaluated in the
    controller as multi-window burn rates (SRE-style: a fast window
    catches sharp regressions, a slow window filters blips — both must
    burn before the deployment is declared violating).

    A request is "bad" when it finished over `target_p99_s`, raised an
    application error, was shed by admission control, or exceeded its
    deadline. burn rate = bad_fraction / (1 - slo): burn 1.0 consumes
    the error budget exactly at the sustainable rate; sustained burn
    above `burn_threshold` trips `ray_tpu_serve_slo_violations_total`
    and — when the deployment also has an AutoscalingConfig — scales it
    up BEFORE the bounded queue starts shedding."""

    target_p99_s: float = 1.0     # per-request latency target
    slo: float = 0.99             # fraction that must be good (budget=1-slo)
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 2.0
    # Minimum fast-window sample count before burn is trusted: one slow
    # request out of one must not page/scale anything.
    min_samples: int = 10
    # Burn-driven upscale cadence (independent of AutoscalingConfig's
    # upscale_delay_s — burn is already a sustained, windowed signal).
    upscale_cooldown_s: float = 10.0
    # Burn-driven DOWNSCALE: with an SLO configured, the queue policy may
    # only shrink the deployment when burn has stayed under idle_burn_max
    # in BOTH windows for a full slow window — and then one replica per
    # downscale_cooldown_s. Burning deployments never scale down.
    idle_burn_max: float = 0.1
    downscale_cooldown_s: float = 30.0


@dataclass
class ServeConfig:
    """Cluster-level serve control-plane knobs, applied via
    ``serve.start(config=ServeConfig(...))`` and PERSISTED to the serve
    KV namespace — a restarted controller recovers with the operator's
    settings, not the defaults (recovery is exactly when they matter)."""

    # Per-replica health-probe timeout during controller recovery
    # (reattach-first: rows whose probe exceeds this are replaced).
    # Raise it on clusters where replica processes respond slowly under
    # recovery load; was a hardcoded 5 s before this knob existed.
    recovery_probe_timeout_s: float = 5.0


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000


@dataclass
class gRPCOptions:
    """Binary-RPC ingress options (reference: serve gRPCOptions)."""
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    # Admission control: requests beyond max_ongoing_requests queue on
    # the replica up to this bound, then shed (drop-newest) with a typed
    # BackPressureError / HTTP 503. -1 = unbounded (legacy behavior).
    max_queued_requests: int = -1
    # Queue-preserving failover: True asserts the deployment's handlers
    # are replay-safe (idempotent), letting the router re-route a
    # dispatched-but-unfinished request to a healthy replica when its
    # replica dies or its slice gang-drains. False (default) fails such
    # requests fast with a typed ReplicaDiedError — mirroring the RPC
    # layer's @rpc.idempotent replay gating.
    request_replay: bool = False
    # Default end-to-end deadline applied to every request through a
    # handle (None = no deadline). Propagated handle -> replica: a
    # timed-out request is cancelled ON the replica instead of burning
    # TPU time; per-call handle.options(timeout_s=...) overrides.
    request_timeout_s: Optional[float] = None
    # Spread replicas across TPU-slice fault domains (slice_id gangs)
    # so one slice preemption never takes the whole deployment. Only
    # applies when the cluster exposes >= 2 slice domains and the
    # deployment doesn't pin placement itself.
    slice_spread: bool = True
    # Latency/error SLO evaluated in the controller (burn-rate engine,
    # serve/slo.py). None = no SLO tracking for this deployment.
    slo_config: Optional["SLOConfig"] = None
