"""Serve configuration schemas (reference: python/ray/serve/config.py,
serve/schema.py — dataclasses here instead of pydantic)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth-driven replica autoscaling
    (reference: serve/autoscaling_policy.py)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0

    def decide(self, num_replicas: int, total_ongoing: float) -> int:
        """Desired replica count from current load."""
        if num_replicas == 0:
            return self.min_replicas
        per = total_ongoing / num_replicas
        desired = num_replicas
        if per > self.target_ongoing_requests:
            import math
            desired = math.ceil(
                total_ongoing / self.target_ongoing_requests)
        elif per < self.target_ongoing_requests / 2:
            import math
            desired = max(1, math.ceil(
                total_ongoing / self.target_ongoing_requests))
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000


@dataclass
class gRPCOptions:
    """Binary-RPC ingress options (reference: serve gRPCOptions)."""
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
