"""Durable serve control-plane state: the controller's write-ahead store.

Reference parity: python/ray/serve/_private/storage/kv_store.py +
controller checkpointing (serve/_private/controller.py persists target
state to the GCS internal KV and *recovers* running replicas instead of
restarting them). Every controller mutation — deploy / delete / scale /
autoscale decision / SLO config — persists a schema-versioned record
here BEFORE the controller publishes any routing or replica effect, and
every live replica keeps a registry row (deployment, replica id, actor
id, version, node / slice domain, swap link). A restarted controller
loads this state, reattaches the still-live ReplicaActors, and
reconciles — only version-mismatched or unhealthy replicas are
replaced.

Keys (GCS KV, ``serve`` namespace):

    target/{app}/{deployment}      -> deployment target record
    replica/{app}/{deployment}/{replica_id} -> live-replica registry row
    routes                         -> route_prefix -> (app, ingress)
    proxies                        -> persisted proxy actor bindings

Records are pickled dicts stamped with ``schema``; a loader skips
records from a NEWER schema (a rolled-back controller must not
misread state a newer one wrote) and upgrades older ones in place.

The store has two faces: synchronous loads/puts for the controller
constructor (which runs on the worker's exec pool, where blocking on
the core loop is legal) and awaitable puts/deletes for the controller's
method bodies (which run ON the core loop). With no core worker at all
(bare unit tests) it degrades to a process-local dict so controller
logic stays unit-testable.
"""

from __future__ import annotations

import logging
import pickle
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1
NAMESPACE = "serve"

# Process-local fallback when no core worker exists (bare unit tests):
# namespace semantics preserved so tests exercise real key handling.
_local_store: Dict[bytes, bytes] = {}


def target_key(app: str, deployment: str) -> bytes:
    return f"target/{app}/{deployment}".encode()

def replica_key(app: str, deployment: str, replica_id: str) -> bytes:
    return f"replica/{app}/{deployment}/{replica_id}".encode()

def app_key(app: str) -> bytes:
    return f"app/{app}".encode()


ROUTES_KEY = b"routes"
PROXIES_KEY = b"proxies"
# Operator-set ServeConfig fields (serve.start(config=...)): persisted so
# a recovered controller keeps the operator's control-plane knobs.
CONFIG_KEY = b"serve_config"


def encode(record: dict) -> bytes:
    rec = dict(record)
    rec.setdefault("schema", SCHEMA_VERSION)
    return pickle.dumps(rec)


def decode(blob: Optional[bytes]) -> Optional[dict]:
    """None for missing/unreadable records and records written by a
    NEWER schema (rolled-back controller: treat as absent rather than
    misinterpret fields)."""
    if blob is None:
        return None
    try:
        rec = pickle.loads(blob)
    except Exception:  # noqa: BLE001 — torn/foreign record: skip it
        logger.warning("unreadable serve state record dropped")
        return None
    if not isinstance(rec, dict) or rec.get("schema", 0) > SCHEMA_VERSION:
        return None
    return rec


class ServeStateStore:
    """KV facade bound to this process's core worker (or the local
    fallback dict)."""

    def __init__(self):
        self._core = None
        try:
            from ray_tpu._private import worker_api
            self._core = worker_api.peek_core()
        except Exception:  # noqa: BLE001 — no core: unit-test fallback
            self._core = None

    # ------------------------------------------------------ sync face
    def _sync(self, coro, timeout: float = 30):
        from ray_tpu._private import worker_api
        return worker_api._call_on_core_loop(self._core, coro, timeout)

    def load_all(self) -> Dict[bytes, dict]:
        """Every serve-namespace record, decoded. Used once, by the
        controller constructor (exec pool — blocking is legal there).
        One cross-loop hop: the key list + all gets run concurrently on
        the core loop, so recovery load is O(1) round trips from the
        constructor's thread, not O(keys)."""
        out: Dict[bytes, dict] = {}
        if self._core is None:
            items = list(_local_store.items())
        else:
            core = self._core

            async def _fetch():
                import asyncio
                keys = await core.gcs.request(
                    "kv_keys", {"namespace": NAMESPACE, "prefix": b""})
                blobs = await asyncio.gather(*[
                    core.gcs.request("kv_get",
                                     {"namespace": NAMESPACE, "key": k})
                    for k in keys])
                return list(zip(keys, blobs))

            items = self._sync(_fetch(), timeout=60)
        for k, blob in items:
            rec = decode(blob)
            if rec is not None:
                out[k] = rec
        return out

    def put_sync(self, key: bytes, record: dict) -> None:
        if self._core is None:
            _local_store[key] = encode(record)
            return
        self._sync(self._core.gcs.request("kv_put", {
            "namespace": NAMESPACE, "key": key, "value": encode(record),
            "overwrite": True}))

    def delete_sync(self, key: bytes) -> None:
        """Constructor-context delete (recovery's app-snapshot reconcile
        drops target records the snapshot says were being removed)."""
        if self._core is None:
            _local_store.pop(key, None)
            return
        self._sync(self._core.gcs.request("kv_del", {
            "namespace": NAMESPACE, "key": key}))

    # ----------------------------------------------------- async face
    async def put(self, key: bytes, record: dict) -> None:
        """Write-ahead put: callers await this BEFORE publishing the
        mutation's effects (routing/replica changes)."""
        if self._core is None:
            _local_store[key] = encode(record)
            return
        from ray_tpu._private import worker_api
        await worker_api.internal_kv_put_async(
            self._core, key, encode(record), namespace=NAMESPACE)

    async def delete(self, key: bytes) -> None:
        if self._core is None:
            _local_store.pop(key, None)
            return
        from ray_tpu._private import worker_api
        await worker_api.internal_kv_del_async(
            self._core, key, namespace=NAMESPACE)

    def delete_soon(self, key: bytes) -> None:
        """Fire-and-forget delete for registry GC from sync contexts
        (a stale registry row is harmless: recovery health-probes every
        row and discards the dead)."""
        if self._core is None:
            _local_store.pop(key, None)
            return
        import asyncio
        try:
            asyncio.ensure_future(self.delete(key))
        except RuntimeError:  # no running loop (sync unit tests)
            pass

    async def delete_prefix(self, prefix: bytes) -> int:
        keys = await self.keys(prefix)
        for k in keys:
            await self.delete(k)
        return len(keys)

    async def keys(self, prefix: bytes = b"") -> List[bytes]:
        if self._core is None:
            return [k for k in _local_store if k.startswith(prefix)]
        from ray_tpu._private import worker_api
        return list(await worker_api.internal_kv_keys_async(
            self._core, prefix, namespace=NAMESPACE))

    async def get(self, key: bytes) -> Optional[dict]:
        if self._core is None:
            return decode(_local_store.get(key))
        from ray_tpu._private import worker_api
        return decode(await worker_api.internal_kv_get_async(
            self._core, key, namespace=NAMESPACE))


def target_record(app: str, name: str, blob: bytes, config: Any,
                  version: str, target_num: int) -> dict:
    return {"schema": SCHEMA_VERSION, "app": app, "name": name,
            "blob": blob, "config": config, "version": version,
            "target_num": int(target_num)}


def app_snapshot_record(app: str, target_records: List[dict],
                        route_prefix: Any, ingress: str) -> dict:
    """ONE KV value describing a whole app deploy — every deployment's
    target record plus the route binding, written atomically BEFORE the
    per-deployment records. A controller crash between two per-
    deployment writes of a multi-deployment app can no longer recover a
    cross-deployment version mix: recovery reconciles stragglers against
    this snapshot (the reference-style app checkpoint)."""
    return {"schema": SCHEMA_VERSION, "app": app,
            "deployments": [dict(r) for r in target_records],
            "route_prefix": route_prefix, "ingress": ingress}


def replica_record(app: str, deployment: str, replica_id: str,
                   actor_id: Any, version: str, state: str,
                   node_id: Any = None, target_slice: str = "",
                   replaces: Optional[str] = None) -> dict:
    """One live-replica registry row. ``replaces`` carries the rolling
    update's swap step: a crash mid-update resumes replace-then-drain
    from this link instead of restarting the rollout."""
    return {"schema": SCHEMA_VERSION, "app": app, "deployment": deployment,
            "replica_id": replica_id, "actor_id": actor_id,
            "version": version, "state": state, "node_id": node_id,
            "target_slice": target_slice, "replaces": replaces}
