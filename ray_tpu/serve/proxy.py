"""HTTP proxy: asyncio HTTP/1.1 server routing to deployment handles.

Reference parity: python/ray/serve/_private/proxy.py (HTTPProxy :745,
ProxyActor :1109) — built on asyncio streams instead of uvicorn (no external
deps). Routes by longest matching route_prefix from the controller's route
table; request bodies are handed to the ingress deployment as a Request.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlsplit


@dataclass
class Request:
    method: str
    path: str
    query: Dict[str, list]
    headers: Dict[str, str]
    body: bytes = b""

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    @property
    def query_params(self) -> Dict[str, str]:
        return {k: v[0] for k, v in self.query.items()}


class ProxyActor:
    ROUTE_REFRESH_S = 1.0

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._server = None
        self._routes: Dict[str, tuple] = {}
        self._handles: Dict[tuple, Any] = {}
        self._last_refresh = 0.0
        self._num_requests = 0

    async def ready(self):
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._port)
        return self._port

    async def _refresh_routes(self):
        now = time.monotonic()
        if now - self._last_refresh < self.ROUTE_REFRESH_S:
            return
        self._last_refresh = now
        from ray_tpu.serve.api import _get_controller_async
        ctrl = await _get_controller_async()
        self._routes = await ctrl.get_route_table.remote()

    def _match_route(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(
                    norm if norm == "/" else norm + "/"):
                if best is None or len(norm) > len(best[0]):
                    best = (norm, target)
        return best

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            parts = request_line.decode("latin1").strip().split()
            if len(parts) != 3:
                await self._respond(writer, 400, b"bad request")
                return
            method, target, _version = parts
            headers: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            url = urlsplit(target)
            path = url.path
            await self._refresh_routes()
            if path == "/-/routes":
                await self._respond(writer, 200, json.dumps(
                    {k: v[0] for k, v in self._routes.items()}).encode())
                return
            if path == "/-/healthz":
                await self._respond(writer, 200, b"success")
                return
            match = self._match_route(path)
            if match is None:
                await self._respond(writer, 404,
                                    f"no route for {path}".encode())
                return
            prefix, (app_name, ingress) = match
            key = (app_name, ingress)
            handle = self._handles.get(key)
            if handle is None:
                from ray_tpu.serve.handle import DeploymentHandle
                handle = DeploymentHandle(ingress, app_name=app_name)
                self._handles[key] = handle
            sub_path = path[len(prefix):] if prefix != "/" else path
            req = Request(method=method, path=sub_path or "/",
                          query=parse_qs(url.query), headers=headers,
                          body=body)
            self._num_requests += 1
            try:
                resp = handle.remote(req)
                result = await resp
            except Exception as e:
                await self._respond(writer, 500, repr(e).encode())
                return
            await self._send_result(writer, result)
        except Exception:
            try:
                await self._respond(writer, 500, b"internal error")
            except Exception:
                pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _send_result(self, writer, result):
        if isinstance(result, bytes):
            await self._respond(writer, 200, result,
                                ctype="application/octet-stream")
        elif isinstance(result, str):
            await self._respond(writer, 200, result.encode(),
                                ctype="text/plain")
        else:
            await self._respond(writer, 200,
                                json.dumps(_jsonable(result)).encode(),
                                ctype="application/json")

    async def _respond(self, writer, code: int, body: bytes,
                       ctype: str = "text/plain"):
        status = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error"}.get(code, "OK")
        writer.write(
            f"HTTP/1.1 {code} {status}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()

    def get_num_requests(self):
        return self._num_requests


def _jsonable(x):
    import numpy as np
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    try:
        import jax
        if isinstance(x, jax.Array):
            return np.asarray(x).tolist()
    except ImportError:
        pass
    return x
