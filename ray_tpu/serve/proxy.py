"""HTTP proxy: asyncio HTTP/1.1 server routing to deployment handles.

Reference parity: python/ray/serve/_private/proxy.py (HTTPProxy :745,
ProxyActor :1109) — built on asyncio streams instead of uvicorn (no external
deps). Routes by longest matching route_prefix from the controller's route
table; request bodies are handed to the ingress deployment as a Request.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlsplit


class Request:
    """HTTP request as seen by a deployment handler.

    Large bodies (>= the serve_body object-plane threshold) travel
    proxy->replica as out-of-band SharedPayload buffers: written once
    into the node's shm store and deserialized on the replica as a
    zero-copy view. `body` materializes bytes lazily (one copy, only if
    the handler asks); `body_view` is the no-copy path.
    """

    def __init__(self, method: str, path: str, query: Dict[str, list],
                 headers: Dict[str, str], body=b"", ws: Any = None,
                 wrap_response: bool = False):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self._body = body
        # WebSocketChannel on upgraded connections (method == "WEBSOCKET"):
        # the handler awaits request.ws.receive() for client messages and
        # yields to send (serve/websocket.py).
        self.ws = ws
        # Set by the proxy: large bytes results come back plane-routed
        # (the replica wraps them; only the proxy unwraps, so direct
        # handle.remote() callers keep plain-bytes results).
        self.wrap_response = wrap_response

    @property
    def body(self) -> bytes:
        from ray_tpu._private import object_plane
        if not isinstance(self._body, bytes):
            self._body = object_plane.body_bytes(self._body)
        return self._body

    @property
    def body_view(self) -> memoryview:
        from ray_tpu._private import object_plane
        return object_plane.body_view(self._body)

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    @property
    def query_params(self) -> Dict[str, str]:
        return {k: v[0] for k, v in self.query.items()}


def _error_response(e: BaseException):
    """Map a request-path error to (status, body, content_type): typed
    serve errors carry their HTTP status (503 shed / replica died, 504
    deadline) and a JSON body with the gRPC-style code; anything else is
    a plain 500."""
    from ray_tpu.serve.exceptions import ServeError, unwrap
    err = unwrap(e)
    if isinstance(err, ServeError):
        body = json.dumps({
            "error": type(err).__name__,
            "code": err.code,
            "message": str(err),
        }).encode()
        return err.http_status, body, "application/json"
    return 500, repr(e).encode(), "text/plain"


class ProxyActor:
    ROUTE_REFRESH_S = 1.0

    # /-/healthz stays ready as long as the controller answered a route
    # refresh this recently; past it, readiness requires a live probe.
    HEALTHZ_GRACE_S = 10.0

    # Proxy autonomy: with the controller down (crash, restart, recovery
    # in progress) the proxy keeps serving its last-known route table —
    # requests route from stale state and the handles' own stale routing
    # keeps them flowing to live replicas. Readiness only flips once the
    # outage outlives this bound (the table is then too old to trust).
    ROUTE_STALE_MAX_S = 60.0

    # One controller round trip must never block a request: past this the
    # refresh attempt is abandoned and the stale table serves.
    CTRL_TIMEOUT_S = 2.0

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._server = None
        self._routes: Dict[str, tuple] = {}
        self._handles: Dict[tuple, Any] = {}
        self._streaming: Dict[tuple, bool] = {}  # ingress -> generator?
        self._last_refresh = 0.0
        self._ctrl_ok_ts = 0.0      # last successful controller round trip
        self._num_requests = 0
        self._ws_queues: Dict[str, asyncio.Queue] = {}
        # Proxy-side SLO accounting: per-deployment queue-wait budget
        # (the SLO latency target, fetched with the route table) and a
        # decayed-max sample of this proxy's event-loop lag. A request's
        # ingress->dispatch queue wait is measured as (dispatch - recv)
        # PLUS the current lag: a blocked proxy loop delays accept/parse
        # BEFORE any stamp we control runs, so wall-clock deltas alone
        # are blind to exactly the stall this accounting exists to see.
        self._slo_targets: Dict[str, float] = {}
        self._loop_lag = 0.0
        self._lag_task = None

    async def ready(self):
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._port)
            try:
                from ray_tpu.util import metrics
                metrics.start_loop_lag_probe_once("serve_http_proxy")
            except Exception:  # noqa: BLE001 — lag probe is best-effort
                pass
            if self._lag_task is None:
                self._lag_task = asyncio.ensure_future(self._lag_loop())
        return self._port

    async def _lag_loop(self):
        """Feed the decayed-max loop-lag sample for queue-wait charging.
        Decay keeps a stall visible across the next few requests (the
        ones that queued behind it) without marking the proxy slow
        forever."""
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(0.1)
            lag = max(0.0, loop.time() - t0 - 0.1)
            self._loop_lag = max(lag, self._loop_lag * 0.5)

    def _account_queue_wait(self, deployment: str, t_recv: float) -> None:
        """One dispatched request's ingress->dispatch queue wait into the
        proxy SLO counters. These ship with the metrics frames; the
        controller folds them into DeploymentSLO as a pseudo-replica, so
        burn fires on proxy-only queueing delay too."""
        from ray_tpu.util import metrics
        metrics.Counter(
            "ray_tpu_serve_proxy_requests_total",
            "requests dispatched to a deployment by this proxy",
            tag_keys=("Deployment",)).inc(1, tags={"Deployment": deployment})
        target = self._slo_targets.get(deployment)
        if not target:
            return
        qw = max(0.0, time.time() - t_recv) + self._loop_lag
        if qw > target:
            metrics.Counter(
                "ray_tpu_serve_proxy_queue_slow_total",
                "dispatched requests whose proxy-side queue wait alone "
                "exceeded the deployment's SLO latency target",
                tag_keys=("Deployment",)).inc(
                1, tags={"Deployment": deployment})

    async def debug_stall(self, seconds: float):
        """Test hook: block THIS proxy's event loop (chaos/SLO tests
        drive proxy-side queueing without touching replicas)."""
        time.sleep(min(float(seconds), 2.0))  # ray-tpu: noqa(ASYNC-BLOCK): deliberate loop stall for SLO tests
        return True

    async def _refresh_routes(self):
        now = time.monotonic()
        if now - self._last_refresh < self.ROUTE_REFRESH_S:
            return
        self._last_refresh = now
        try:
            from ray_tpu.serve.api import _get_controller_async
            ctrl = await _get_controller_async()
            # Bounded: a restarting controller parks calls until it is
            # back — that wait must never ride a request's latency. The
            # abandoned call completes harmlessly later.
            routes, targets = await asyncio.wait_for(
                asyncio.gather(
                    ctrl.get_route_table.remote().future(),
                    ctrl.get_slo_queue_targets.remote().future()),
                timeout=self.CTRL_TIMEOUT_S)
        except Exception:  # noqa: BLE001 — serve with stale routes;
            return         # /-/healthz flips per _healthz_ready
        self._ctrl_ok_ts = time.monotonic()
        self._slo_targets = targets or {}
        if routes != self._routes:
            # Redeploys may switch a handler generator <-> plain: re-probe.
            self._streaming.clear()
        self._routes = routes

    async def _healthz_ready(self) -> bool:
        """Readiness, re-anchored on recovery progress: controller
        answered recently -> ready; controller unreachable -> probe it
        (a restarted controller answers ping() DURING recovery, which
        re-anchors the grace window); still unreachable -> stay ready on
        the stale route table within ROUTE_STALE_MAX_S."""
        now = time.monotonic()
        if now - self._ctrl_ok_ts < self.HEALTHZ_GRACE_S:
            return True
        try:
            from ray_tpu.serve.api import _get_controller_async
            ctrl = await _get_controller_async()
            await asyncio.wait_for(ctrl.ping.remote().future(),
                                   timeout=self.CTRL_TIMEOUT_S)
            self._ctrl_ok_ts = time.monotonic()
            return True
        except Exception:  # noqa: BLE001 — controller really down
            pass
        return bool(self._routes) and \
            now - self._ctrl_ok_ts < self.ROUTE_STALE_MAX_S

    def _match_route(self, path: str):
        best = None
        for prefix, target in self._routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(
                    norm if norm == "/" else norm + "/"):
                if best is None or len(norm) > len(best[0]):
                    best = (norm, target)
        return best

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            request_line = await reader.readline()
            if not request_line:
                return
            parts = request_line.decode("latin1").strip().split()
            if len(parts) != 3:
                await self._respond(writer, 400, b"bad request")
                return
            method, target, _version = parts
            headers: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            t_recv = time.time()   # request fully parsed off the socket
            url = urlsplit(target)
            path = url.path
            await self._refresh_routes()
            if path == "/-/routes":
                await self._respond(writer, 200, json.dumps(
                    {k: v[0] for k, v in self._routes.items()}).encode())
                return
            if path == "/-/healthz":
                # Readiness = the control plane is reachable OR the proxy
                # can still serve autonomously from bounded-stale routes
                # (controller crash/recovery window). Rolling updates keep
                # this green: replicas swap replace-then-drain.
                if await self._healthz_ready():
                    await self._respond(writer, 200, b"success")
                else:
                    await self._respond(
                        writer, 503, b"unhealthy: controller unreachable "
                        b"and route table stale")
                return
            match = self._match_route(path)
            if match is None:
                # A just-deployed route may not be in this proxy's table
                # yet (refresh window, or another request's refresh still
                # in flight holding the timestamp): force one refresh and
                # re-check before 404ing.
                self._last_refresh = 0.0
                await self._refresh_routes()
                match = self._match_route(path)
            if match is None:
                await self._respond(writer, 404,
                                    f"no route for {path}".encode())
                return
            if (headers.get("upgrade", "").lower() == "websocket"
                    and "sec-websocket-key" in headers):
                await self._handle_websocket(reader, writer, match, path,
                                             url, headers)
                return
            prefix, (app_name, ingress) = match
            key = (app_name, ingress)
            handle = self._handle_for(key)
            # Multiplexing through the front door: the reference's
            # serve_multiplexed_model_id header tags the request with a
            # model id, which rides handle.options into mux-aware
            # routing (model-resident replica preferred).
            mux_id = headers.get("serve_multiplexed_model_id", "")
            if mux_id:
                handle = handle.options(multiplexed_model_id=mux_id)
            sub_path = self._sub_path(prefix, path)
            from ray_tpu._private import object_plane
            req = Request(method=method, path=sub_path or "/",
                          query=parse_qs(url.query), headers=headers,
                          body=object_plane.wrap_body(body),
                          wrap_response=True)
            self._num_requests += 1
            # Request trace: minted HERE (or adopted from the client's
            # X-Request-Id), bound to the task context so the handle —
            # and through it the replica and anything the handler spawns
            # — joins the same trace.
            from ray_tpu.serve import request_trace
            trace = request_trace.mint(
                ingress, request_id=headers.get("x-request-id", ""))
            trace.stamp(request_trace.RQ_PROXY_RECV, t_recv)
            trace_token = request_trace.bind(trace)
            try:
                streaming = self._streaming.get(key)
                if streaming is None:
                    # One probe per ingress: is the handler a generator
                    # function? (reference: proxy.py checks the response
                    # type; here the replica inspects its callable once.)
                    # A failed probe (e.g. empty replica set mid-rollout)
                    # is NOT cached: the next request retries it.
                    try:
                        streaming = await self._probe_streaming(handle)
                        self._streaming[key] = streaming
                    except Exception:
                        streaming = False
                self._account_queue_wait(ingress, t_recv)
                if streaming:
                    try:
                        gen = handle.options(stream=True).remote(req)
                        await self._send_stream(writer, gen, trace=trace)
                    except Exception as e:
                        from ray_tpu.serve.exceptions import unwrap
                        trace.error = type(unwrap(e)).__name__
                        code, body, ctype = _error_response(e)
                        await self._respond(writer, code, body, ctype=ctype,
                                            request_id=trace.request_id)
                    return
                try:
                    resp = handle.remote(req)
                    result = await resp
                except Exception as e:
                    from ray_tpu.serve.exceptions import unwrap
                    trace.error = type(unwrap(e)).__name__
                    code, body, ctype = _error_response(e)
                    await self._respond(writer, code, body, ctype=ctype,
                                        request_id=trace.request_id)
                    return
                await self._send_result(writer, result,
                                        request_id=trace.request_id)
            finally:
                request_trace.unbind(trace_token)
                request_trace.finish(trace, "proxy")
        except Exception:
            try:
                await self._respond(writer, 500, b"internal error")
            except Exception:
                pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _handle_for(self, key):
        handle = self._handles.get(key)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle
            handle = DeploymentHandle(key[1], app_name=key[0])
            self._handles[key] = handle
        return handle

    @staticmethod
    def _sub_path(prefix: str, path: str) -> str:
        return (path[len(prefix):] if prefix != "/" else path) or "/"

    # ------------------------------------------------- websockets

    def _self_handle(self):
        from ray_tpu._private import worker_api
        from ray_tpu.actor import ActorHandle
        return ActorHandle(worker_api.get_core().current_actor_id,
                           class_name="ProxyActor")

    async def _handle_websocket(self, reader, writer, match, path, url,
                                headers):
        """RFC 6455 upgrade + duplex bridge to the replica handler
        (reference: serve's ASGI websocket scope). Handler yields ->
        frames out; client frames -> ws_receive() long-polls."""
        import uuid as _uuid

        from ray_tpu.serve import websocket as ws
        prefix, (app_name, ingress) = match
        handle = self._handle_for((app_name, ingress))
        # Same mux-aware routing as the HTTP branch: a model-id-tagged
        # websocket session prefers a model-resident replica.
        mux_id = headers.get("serve_multiplexed_model_id", "")
        if mux_id:
            handle = handle.options(multiplexed_model_id=mux_id)

        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: "
            + ws.accept_key(headers["sec-websocket-key"]).encode()
            + b"\r\n\r\n")
        await writer.drain()

        conn_id = _uuid.uuid4().hex
        queue: asyncio.Queue = asyncio.Queue()
        self._ws_queues[conn_id] = queue
        self._num_requests += 1

        async def read_loop():
            try:
                while True:
                    opcode, payload = await ws.read_frame(reader)
                    if opcode == ws.OP_PING:
                        writer.write(ws.encode_frame(ws.OP_PONG, payload))
                        await writer.drain()
                    elif opcode == ws.OP_TEXT:
                        await queue.put(payload.decode())
                    elif opcode == ws.OP_BINARY:
                        await queue.put(payload)
                    elif opcode == ws.OP_CLOSE:
                        await queue.put(None)
                        return
            except ws.FrameTooLarge:
                # 1009 = Message Too Big; drop the connection (the
                # declared bytes were never read, so the stream is
                # unsynchronized beyond recovery anyway).
                try:
                    writer.write(ws.encode_frame(ws.OP_CLOSE, b"\x03\xf1"))
                    await writer.drain()
                    writer.close()
                except (ConnectionError, OSError):
                    pass
                await queue.put(None)
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                await queue.put(None)

        reader_task = asyncio.ensure_future(read_loop())
        req = Request(method="WEBSOCKET", path=self._sub_path(prefix, path),
                      query=parse_qs(url.query), headers=headers,
                      ws=ws.WebSocketChannel(self._self_handle(), conn_id))
        # Websocket sessions trace like any request: upgrade = proxy_recv,
        # first frame out = first_item, session close = reply.
        from ray_tpu.serve import request_trace
        trace = request_trace.mint(
            ingress, request_id=headers.get("x-request-id", ""))
        trace.stamp(request_trace.RQ_PROXY_RECV)
        trace_token = request_trace.bind(trace)
        try:
            gen = handle.options(stream=True).remote(req)
            async for item in gen:
                if trace.phases[request_trace.RQ_FIRST_ITEM] is None:
                    trace.stamp(request_trace.RQ_FIRST_ITEM)
                if isinstance(item, str):
                    frame = ws.encode_frame(ws.OP_TEXT, item.encode())
                else:
                    frame = ws.encode_frame(
                        ws.OP_BINARY,
                        item if isinstance(item, bytes) else
                        json.dumps(_jsonable(item)).encode())
                writer.write(frame)
                await writer.drain()
            writer.write(ws.encode_frame(ws.OP_CLOSE, b"\x03\xe8"))
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        except Exception as e:
            # Typed close codes: 1012 Service Restart when the replica
            # died / is draining (client should reconnect), 1013 Try
            # Again Later on backpressure, 1011 otherwise.
            from ray_tpu import exceptions as exc
            from ray_tpu.serve.exceptions import (BackPressureError,
                                                  ReplicaDiedError,
                                                  ReplicaDrainingError,
                                                  unwrap)
            err = unwrap(e)
            if isinstance(err, (ReplicaDiedError, ReplicaDrainingError,
                                exc.ActorDiedError, exc.ActorUnavailableError,
                                exc.WorkerCrashedError)):
                code = 1012
            elif isinstance(err, BackPressureError):
                code = 1013
            else:
                code = 1011
            try:
                writer.write(ws.encode_frame(
                    ws.OP_CLOSE, code.to_bytes(2, "big")))
                await writer.drain()
            except Exception:
                pass
        finally:
            request_trace.unbind(trace_token)
            request_trace.finish(trace, "proxy")
            reader_task.cancel()
            self._ws_queues.pop(conn_id, None)

    async def ws_receive(self, conn_id: str, timeout=None):
        """Next client message for an open websocket. Tagged result so
        the channel can distinguish closed from idle:
        {"msg": m} | {"closed": True} | {"timeout": True}.
        Called BY the replica through an actor call."""
        queue = self._ws_queues.get(conn_id)
        if queue is None:
            return {"closed": True}
        try:
            if timeout is not None:
                msg = await asyncio.wait_for(queue.get(), timeout)
            else:
                msg = await queue.get()
        except asyncio.TimeoutError:
            return {"timeout": True}
        if msg is None:
            await queue.put(None)  # keep returning closed
            return {"closed": True}
        return {"msg": msg}

    async def _probe_streaming(self, handle) -> bool:
        router = handle._get_router()
        await router.refresh_async()
        try:
            _i, replica = router.pick_cached()
        except RuntimeError:
            # Shared-router race: a concurrent request's refresh holds
            # the throttle window while its controller round trip is
            # still in flight, so this coroutine saw an empty cached
            # set. Force one authoritative refresh — a failed probe
            # would fall back to the UNARY path, which breaks streaming
            # handlers for this request.
            await router.refresh_async(force=True)
            _i, replica = router.pick_cached()
        try:
            return bool(await replica.is_streaming_method.remote(
                handle._method))
        finally:
            router.release(_i)

    @staticmethod
    def _as_chunk(item) -> bytes:
        from ray_tpu._private.object_plane import SharedPayload
        if isinstance(item, SharedPayload):
            return item.to_bytes()
        if isinstance(item, bytes):
            return item
        if isinstance(item, str):
            return item.encode()
        return (json.dumps(_jsonable(item)) + "\n").encode()

    async def _send_stream(self, writer, gen, trace=None):
        """Chunked transfer encoding: each generator item is flushed as its
        own chunk the moment the replica yields it (reference: proxy.py
        :745 ASGI streaming responses).

        The FIRST item (which also runs the deferred routing) is awaited
        BEFORE the 200/chunked headers go out, so routing or immediate
        handler errors still produce a clean 500 (they propagate to the
        caller). A mid-stream failure after headers cannot inject a status
        line into the chunk framing — the connection just closes, which a
        chunked client sees as a truncated stream."""
        it = gen.__aiter__()
        have_first = True
        try:
            first = await it.__anext__()
        except StopAsyncIteration:
            have_first = False
        if trace is not None and have_first:
            from ray_tpu.serve import request_trace
            trace.stamp(request_trace.RQ_FIRST_ITEM)
        req_id_hdr = (f"X-Request-Id: {trace.request_id}\r\n".encode()
                      if trace is not None else b"")
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: application/octet-stream\r\n"
                     + req_id_hdr +
                     b"Transfer-Encoding: chunked\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        try:
            if have_first:
                chunk = self._as_chunk(first)
                if chunk:
                    writer.write(f"{len(chunk):x}\r\n".encode()
                                 + chunk + b"\r\n")
                    await writer.drain()
            async for item in it:
                chunk = self._as_chunk(item)
                if not chunk:
                    continue  # an empty chunk would terminate the stream
                writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:
            return  # headers sent: truncate, never write a 500 mid-stream

    async def _send_result(self, writer, result, request_id: str = ""):
        from ray_tpu._private.object_plane import SharedPayload
        if isinstance(result, SharedPayload):
            # Plane-routed large body: the view aliases the shm segment
            # (pinned through the handle's materialized value) and goes
            # straight to the socket — no copy on the proxy at all.
            await self._respond(writer, 200, result.view,
                                ctype="application/octet-stream",
                                request_id=request_id)
        elif isinstance(result, bytes):
            await self._respond(writer, 200, result,
                                ctype="application/octet-stream",
                                request_id=request_id)
        elif isinstance(result, str):
            await self._respond(writer, 200, result.encode(),
                                ctype="text/plain", request_id=request_id)
        else:
            await self._respond(writer, 200,
                                json.dumps(_jsonable(result)).encode(),
                                ctype="application/json",
                                request_id=request_id)

    async def _respond(self, writer, code: int, body,
                       ctype: str = "text/plain", request_id: str = ""):
        status = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, "OK")
        rid = f"X-Request-Id: {request_id}\r\n" if request_id else ""
        nbytes = body.nbytes if isinstance(body, memoryview) else len(body)
        writer.write(
            f"HTTP/1.1 {code} {status}\r\n"
            f"Content-Type: {ctype}\r\n{rid}"
            f"Content-Length: {nbytes}\r\n"
            f"Connection: close\r\n\r\n".encode())
        # Body written as its own frame: a memoryview body (zero-copy
        # plane view) must not be concatenated into the header bytes.
        writer.write(body)
        await writer.drain()

    def get_num_requests(self):
        return self._num_requests


def _jsonable(x):
    import numpy as np
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    try:
        import jax
        if isinstance(x, jax.Array):
            return np.asarray(x).tolist()
    except ImportError:
        pass
    return x
