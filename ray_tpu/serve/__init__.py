"""ray_tpu.serve: scalable model serving on actors.

Reference parity: python/ray/serve (serve.run api.py:523, ServeController
_private/controller.py:91, replicas _private/replica.py:233, power-of-two
router _private/replica_scheduler/pow_2_scheduler.py:44, batching
serve/batching.py, multiplexing serve/multiplex.py). Replicas are async
ray_tpu actors; the TPU-first twist is that a replica typically holds a
jitted JAX callable and `@serve.batch` feeds it fixed-size batches to avoid
recompilation.
"""

from ray_tpu.serve.api import (delete, get_app_handle, get_deployment_handle,
                               get_grpc_address, run, shutdown, start,
                               status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.continuous_batching import (BatchScheduler,
                                               continuous_batching)
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.config import (AutoscalingConfig, HTTPOptions,
                                  ServeConfig, SLOConfig, gRPCOptions)
from ray_tpu.serve import request_trace
from ray_tpu.serve.grpc_proxy import ServeRpcClient
from ray_tpu.serve.handle import (DeploymentHandle, DeploymentResponse,
                                  DeploymentResponseGenerator)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.config_deploy import (deploy_config, import_application,
                                         load_serve_config,
                                         run_import_path)
from ray_tpu.serve.exceptions import (BackPressureError, ReplicaDiedError,
                                      RequestTimeoutError, ServeError)

__all__ = [
    "deployment", "Deployment", "Application", "run", "start", "shutdown",
    "delete", "status", "get_app_handle", "get_deployment_handle",
    "get_grpc_address", "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "ServeRpcClient", "batch",
    "continuous_batching", "BatchScheduler", "multiplexed",
    "get_multiplexed_model_id", "request_trace",
    "AutoscalingConfig", "ServeConfig", "SLOConfig",
    "HTTPOptions",
    "gRPCOptions", "deploy_config", "import_application",
    "load_serve_config", "run_import_path", "ServeError",
    "BackPressureError", "RequestTimeoutError", "ReplicaDiedError",
]
