"""CLI: cluster lifecycle, state inspection, job control.

Reference parity: python/ray/scripts/scripts.py (command registry
:2545-2604 — start/stop/status/timeline/job/list). Invoke as
`python -m ray_tpu <command>`.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _address(args) -> str:
    addr = getattr(args, "address", None) or os.environ.get(
        "RAY_TPU_ADDRESS", "")
    if not addr:
        sys.exit("error: --address (or RAY_TPU_ADDRESS) is required")
    return addr


def _connect(args):
    import ray_tpu
    ray_tpu.init(address=_address(args))
    return ray_tpu


# ---------------------------------------------------------------- start/stop

def cmd_start(args):
    """Run a head (GCS + raylet) or worker (raylet) node in the foreground."""
    import asyncio

    from ray_tpu._private.config import Config, set_config
    from ray_tpu._private.node import HeadNode, detect_node_resources
    from ray_tpu._private.raylet import Raylet
    from ray_tpu._private.node import new_session_dir

    config = Config.load(None)
    set_config(config)
    res = detect_node_resources(args.num_cpus, args.num_tpus, None, config)
    if args.memory is not None:
        res["memory"] = float(args.memory)

    async def _run_head():
        head = HeadNode(config, resources=res,
                        object_store_memory=args.object_store_memory)
        gcs_address = await head.start(port=args.port)
        print(f"ray_tpu head started; GCS at {gcs_address}", flush=True)
        print(f"connect with: ray_tpu.init(address='{gcs_address}') or "
              f"RAY_TPU_ADDRESS={gcs_address}", flush=True)
        if args.client_server_port:
            from ray_tpu.util.client import ClientServer
            cs = ClientServer(gcs_address)
            addr = await cs.start(port=args.client_server_port)
            head.client_server = cs
            print(f"client server at ray_tpu://{addr}", flush=True)
        return head

    async def _run_worker():
        session_dir = new_session_dir(config)
        raylet = Raylet(config, args.address, session_dir, resources=res,
                        object_store_memory=args.object_store_memory)
        await raylet.start()
        print(f"ray_tpu worker node joined {args.address}", flush=True)
        return raylet

    async def _main():
        node = await (_run_head() if args.head else _run_worker())
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await node.stop()

    if not args.head and not args.address:
        sys.exit("error: worker nodes need --address=<gcs host:port>")
    asyncio.run(_main())


def cmd_status(args):
    ray_tpu = _connect(args)
    from ray_tpu.util.state import cluster_status
    st = cluster_status()
    print(f"nodes: {st['nodes_alive']} alive, {st['nodes_dead']} dead")
    print("resources:")
    avail = st["available_resources"]
    for k, v in sorted(st["cluster_resources"].items()):
        print(f"  {k}: {avail.get(k, 0):g}/{v:g} available")
    if st["actors"]:
        print("actors:", dict(st["actors"]))
    if st["placement_groups"]:
        print("placement groups:", dict(st["placement_groups"]))
    try:
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        addr = worker_api._call_on_core_loop(
            core, core.gcs.request("get_metrics_address", {}), 10)
        if addr:
            print(f"metrics: http://{addr}/metrics "
                  f"(status: http://{addr}/api/status)")
    except Exception:
        pass
    ray_tpu.shutdown()


# ---------------------------------------------------------------- state

def cmd_list(args):
    ray_tpu = _connect(args)
    from ray_tpu.util import state
    fns = {
        "nodes": state.list_nodes, "actors": state.list_actors,
        "tasks": state.list_tasks, "jobs": state.list_jobs,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }
    rows = fns[args.entity]()
    print(json.dumps(rows, indent=2, default=str))
    ray_tpu.shutdown()


def cmd_summary(args):
    ray_tpu = _connect(args)
    from ray_tpu.util.state import summarize_task_latency, summarize_tasks
    print(json.dumps(summarize_tasks(), indent=2))
    rows = summarize_task_latency()
    if rows:
        # Flight-recorder latency columns: p50/p95 per lifecycle phase.
        print(f"\n{'name':<24}{'phase':<16}{'count':>7}"
              f"{'p50 ms':>10}{'p95 ms':>10}")
        for r in rows:
            print(f"{r['name']:<24.24}{r['phase']:<16}{r['count']:>7}"
                  f"{r['p50_ms']:>10.3f}{r['p95_ms']:>10.3f}")
    ray_tpu.shutdown()


def cmd_timeline(args):
    ray_tpu = _connect(args)
    trace = ray_tpu.timeline()
    rid = getattr(args, "request", None)
    if rid:
        # One serve request's trace only: every row stamped with the
        # request id (proxy/replica hops, replay markers, handler spans).
        trace = [t for t in trace if t.get("request_id") == rid]
    out = args.output or "timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {out}")
    ray_tpu.shutdown()


def cmd_top(args):
    """Live cluster dashboard over the GCS time-series store."""
    from ray_tpu.scripts import top
    top.run(args)


def cmd_traces(args):
    """Search the GCS serve-request trace buffer (slow / failed requests)."""
    ray_tpu = _connect(args)
    from ray_tpu._private import worker_api
    core = worker_api.get_core()
    rows = worker_api._call_on_core_loop(
        core,
        core.gcs.request("search_traces", {
            "deployment": args.deployment,
            "min_ms": args.min_ms,
            "errors_only": args.errors_only,
            "limit": args.limit,
        }), 30)
    if not rows:
        print("no matching requests")
    else:
        print(f"{'request_id':<34}{'deployment':<18}{'ms':>9}"
              f"{'hops':>6}{'replays':>8}  error")
        for r in rows:
            print(f"{r['request_id']:<34.33}{r['deployment']:<18.17}"
                  f"{r['total_ms']:>9.1f}{r['hops']:>6}{r['replays']:>8}"
                  f"  {r.get('error') or ''}")
        print(f"\n{len(rows)} request(s); inspect one with: "
              f"python -m ray_tpu timeline --request <request_id>")
    ray_tpu.shutdown()


def cmd_stack(args):
    """`ray stack` equivalent: thread dumps / CPU samples / heap snapshots
    from a live worker over its profiling RPCs (reference:
    dashboard/modules/reporter/profile_manager.py)."""
    ray_tpu = _connect(args)
    from ray_tpu._private import worker_api
    core = worker_api.get_core()

    method = {"stack": "stack_dump", "cpu": "profile_cpu",
              "memory": "profile_memory"}[args.kind]
    payload = {"duration_s": args.duration} if args.kind == "cpu" else {}

    async def probe():
        return await core.clients.request(args.worker_address, method,
                                          payload, timeout=60)

    out = worker_api._call_on_core_loop(core, probe(), 90)
    if args.kind == "stack":
        for thread, stack in out.items():
            print(f"--- {thread} ---\n{stack}")
    else:
        print(json.dumps(out, indent=2, default=str))
    ray_tpu.shutdown()


def cmd_up(args):
    """`ray up` equivalent: config-driven cluster bring-up, attached
    (head + provider + autoscaler run in this process until Ctrl-C)."""
    import time as _time

    from ray_tpu.autoscaler import create_or_update_cluster

    launcher = create_or_update_cluster(args.config)
    print(f"cluster '{launcher.config['cluster_name']}' up; GCS at "
          f"{launcher.gcs_address}", flush=True)
    print(f"connect with: ray_tpu.init(address='{launcher.gcs_address}')",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("tearing down...", flush=True)
        launcher.teardown()


def cmd_down(args):
    from ray_tpu.autoscaler import load_cluster_config, teardown_cluster
    cfg = load_cluster_config(args.config)
    if cfg["provider"].get("type", "fake") == "fake":
        print("fake-provider clusters live in the `up` process — stop "
              "them with Ctrl-C there; nothing to terminate from here",
              flush=True)
        return
    n = teardown_cluster(args.config)
    print(f"terminated {n} provider node(s)", flush=True)


def cmd_kv_store(args):
    """Standalone external GCS state store (the Redis-equivalent;
    reference: redis_store_client.h). Point heads at it with
    RAY_TPU_GCS_STORAGE_ADDRESS=host:port."""
    from ray_tpu._private.kv_store import run_server
    run_server(args.host, args.port, args.dir)


def cmd_serve(args):
    """Serve CLI (reference: python/ray/serve/scripts.py): deploy a
    config file, run an import path, or print app status — against the
    cluster at --address."""
    ray_tpu = _connect(args)
    from ray_tpu import serve
    if args.serve_cmd == "deploy":
        deployed = serve.deploy_config(args.config)
        print(f"deployed applications: {', '.join(deployed)}")
    elif args.serve_cmd == "run":
        serve.run_import_path(args.import_path, name=args.name,
                              route_prefix=args.route_prefix)
        print(f"app '{args.name}' running at route {args.route_prefix}; "
              f"Ctrl-C to exit", flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            serve.delete(args.name)
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    ray_tpu.shutdown()


# ---------------------------------------------------------------- rllib

def cmd_rllib(args):
    """RLlib CLI (reference: rllib/train.py `rllib train` +
    rllib/evaluate.py `rllib evaluate`): run an algorithm on an env from
    the command line; evaluate a saved checkpoint greedily."""
    import cloudpickle

    import ray_tpu
    from ray_tpu import rllib as rl
    config_cls = getattr(rl, f"{args.algo}Config", None)
    if config_cls is None:
        sys.exit(f"error: unknown algorithm {args.algo!r}; see "
                 f"ray_tpu.rllib.__all__ for available *Config classes")
    config_json = args.config
    if args.rllib_cmd == "evaluate":
        # Usage errors before paying for init + actor spawns.
        if not args.checkpoint_path:
            sys.exit("error: evaluate needs --checkpoint-path")
        with open(args.checkpoint_path, "rb") as f:
            ckpt = cloudpickle.load(f)
        # Train-time config rides in the checkpoint so evaluate builds
        # the SAME network without the user repeating --config.
        if not config_json:
            config_json = ckpt.get("cli_config", "")
        saved_env = ckpt.get("cli_env")
        if saved_env and saved_env != args.env:
            sys.exit(f"error: checkpoint was trained on env "
                     f"{saved_env!r}; pass --env {saved_env}")
    cfg = config_cls().environment(args.env)
    if config_json:
        try:
            overrides = json.loads(config_json)
            if not isinstance(overrides, dict):
                raise ValueError("--config must be a JSON object")
            cfg.training(**overrides)
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            sys.exit(f"error: bad --config: {e}")
    if args.rllib_cmd == "evaluate":
        if cfg.is_multi_agent:
            sys.exit("error: evaluate supports single-policy "
                     "checkpoints only")
        from ray_tpu.rllib.env import make_env
        if make_env(args.env, cfg.env_config).continuous:
            sys.exit("error: evaluate supports discrete-action "
                     "policy/Q algorithms only")
        cfg.env_runners(num_env_runners=1)  # one greedy evaluator
    ray_tpu.init(num_cpus=args.num_cpus, num_tpus=0)
    try:
        algo = cfg.build()
        if args.rllib_cmd == "train":
            best = float("-inf")
            for i in range(args.stop_iters):
                r = algo.step()
                rew = r.get("episode_reward_mean", float("nan"))
                if rew == rew:
                    best = max(best, rew)
                print(f"iter {i + 1}: reward_mean="
                      f"{rew if rew == rew else 'n/a'} "
                      f"episodes={r.get('episodes_total', 0)}", flush=True)
                if args.stop_reward is not None and rew == rew \
                        and rew >= args.stop_reward:
                    print(f"stop-reward {args.stop_reward} reached")
                    break
            if best > float("-inf"):
                print(f"best reward_mean: {best:.2f}")
            if args.checkpoint_path:
                state = algo.save_checkpoint()
                state["cli_config"] = args.config
                state["cli_env"] = args.env
                with open(args.checkpoint_path, "wb") as f:
                    cloudpickle.dump(state, f)
                print(f"checkpoint written to {args.checkpoint_path}")
        else:  # evaluate
            if not hasattr(algo, "learner"):
                sys.exit(f"error: {args.algo} has no single-learner "
                         f"checkpoint to evaluate")
            ckpt.pop("cli_config", None)
            ckpt.pop("cli_env", None)
            algo.load_checkpoint(ckpt)
            weights = algo.learner.get_weights()
            ret = ray_tpu.get(
                algo.env_runners[0].evaluate_return.remote(
                    weights, episodes=args.episodes), timeout=600)
            print(f"mean_return={ret:.2f} over {args.episodes} episodes")
        algo.cleanup()
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- jobs

def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient(_address(args))
    if args.job_cmd == "submit":
        sid = client.submit_job(entrypoint=" ".join(args.entrypoint))
        print(sid)
        if args.wait:
            status = client.wait_until_finish(sid, timeout=args.timeout)
            print(status)
            print(client.get_job_logs(sid), end="")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        print(client.stop_job(args.submission_id))
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))


# ---------------------------------------------------------------- parser

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or worker node")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", default="", help="GCS address (worker mode)")
    s.add_argument("--port", type=int, default=6379)
    s.add_argument("--num-cpus", type=float, default=None, dest="num_cpus")
    s.add_argument("--num-tpus", type=float, default=None, dest="num_tpus")
    s.add_argument("--memory", type=int, default=None,
                   help="node memory resource in bytes")
    s.add_argument("--object-store-memory", type=int, default=None,
                   dest="object_store_memory",
                   help="plasma arena size in bytes")
    s.add_argument("--client-server-port", type=int, default=0,
                   dest="client_server_port",
                   help="serve remote ray_tpu:// clients on this port")
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("status", help="cluster status")
    s.add_argument("--address", default=None)
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("list", help="list cluster entities")
    s.add_argument("entity", choices=["nodes", "actors", "tasks", "jobs",
                                      "objects", "placement-groups"])
    s.add_argument("--address", default=None)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("summary", help="task state summary")
    s.add_argument("--address", default=None)
    s.set_defaults(fn=cmd_summary)

    s = sub.add_parser("timeline", help="dump chrome-trace timeline")
    s.add_argument("--address", default=None)
    s.add_argument("-o", "--output", default=None)
    s.add_argument("--request", default=None,
                   help="filter to one serve request id (X-Request-Id)")
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser("top", help="live cluster dashboard "
                                   "(tsdb-backed, ANSI redraw)")
    s.add_argument("--address", default=None)
    s.add_argument("--once", action="store_true",
                   help="print a single frame and exit (no ANSI)")
    s.add_argument("--interval", type=float, default=2.0)
    s.add_argument("--window", type=float, default=300.0,
                   help="query window in seconds")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser("traces", help="search serve request traces")
    s.add_argument("--address", default=None)
    s.add_argument("--deployment", default=None)
    s.add_argument("--min-ms", type=float, default=0.0,
                   help="only requests slower than this end-to-end")
    s.add_argument("--errors-only", action="store_true")
    s.add_argument("--limit", type=int, default=50)
    s.set_defaults(fn=cmd_traces)

    s = sub.add_parser("profile", help="profile a live worker "
                                       "(stack/cpu/memory)")
    s.add_argument("kind", choices=["stack", "cpu", "memory"])
    s.add_argument("worker_address", help="worker RPC address host:port "
                                          "(see `list workers`)")
    s.add_argument("--address", default=None)
    s.add_argument("--duration", type=float, default=2.0)
    s.set_defaults(fn=cmd_stack)

    s = sub.add_parser("up", help="bring up a cluster from a config YAML")
    s.add_argument("config")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="terminate a cluster's provider nodes")
    s.add_argument("config")
    s.set_defaults(fn=cmd_down)

    s = sub.add_parser("kv-store", help="run the standalone external "
                                        "GCS state store")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--dir", default="/tmp/ray_tpu_kv_store")
    s.set_defaults(fn=cmd_kv_store)

    s = sub.add_parser("serve", help="serve deploy/run/status")
    ssub = s.add_subparsers(dest="serve_cmd", required=True)
    sd = ssub.add_parser("deploy")
    sd.add_argument("config")
    sd.add_argument("--address", default=None)
    sd.set_defaults(fn=cmd_serve)
    sr = ssub.add_parser("run")
    sr.add_argument("import_path")
    sr.add_argument("--name", default="default")
    sr.add_argument("--route-prefix", default="/", dest="route_prefix")
    sr.add_argument("--address", default=None)
    sr.set_defaults(fn=cmd_serve)
    st = ssub.add_parser("status")
    st.add_argument("--address", default=None)
    st.set_defaults(fn=cmd_serve)

    s = sub.add_parser("rllib", help="rllib train/evaluate")
    rsub = s.add_subparsers(dest="rllib_cmd", required=True)
    for name in ("train", "evaluate"):
        r = rsub.add_parser(name)
        r.add_argument("--algo", default="PPO",
                       help="algorithm name (PPO, A2C, PG, DQN, C51, "
                            "QRDQN, ...; evaluate needs a "
                            "discrete-action single-learner algo)")
        r.add_argument("--env", default="CartPole-v1")
        r.add_argument("--config", default="",
                       help="JSON dict of .training(...) overrides")
        r.add_argument("--num-cpus", type=int, default=4,
                       dest="num_cpus")
        r.add_argument("--checkpoint-path", default="",
                       dest="checkpoint_path")
        if name == "train":
            r.add_argument("--stop-iters", type=int, default=10,
                           dest="stop_iters")
            r.add_argument("--stop-reward", type=float, default=None,
                           dest="stop_reward")
        else:
            r.add_argument("--episodes", type=int, default=5)
        r.set_defaults(fn=cmd_rllib)

    s = sub.add_parser("job", help="job submission")
    jsub = s.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address", default=None)
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=300)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    j = jsub.add_parser("status")
    j.add_argument("submission_id")
    j.add_argument("--address", default=None)
    j = jsub.add_parser("logs")
    j.add_argument("submission_id")
    j.add_argument("--address", default=None)
    j = jsub.add_parser("stop")
    j.add_argument("submission_id")
    j.add_argument("--address", default=None)
    j = jsub.add_parser("list")
    j.add_argument("--address", default=None)
    s.set_defaults(fn=cmd_job)

    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
