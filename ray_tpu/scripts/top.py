"""`ray_tpu top`: live terminal dashboard over the GCS time-series store.

Curses-free: each refresh fetches one batched `metrics_query` RPC (all
panels in a single round trip) and repaints with a plain ANSI
home+clear. `--once` prints a single frame without touching the
terminal — scripts and the render smoke test use it.

Panels: per-deployment serve QPS / p99 / SLO burn, compiled-DAG ticks/s
and recoveries, podracer steps/s + weight staleness, object-plane
occupancy/spill, warm-pool hit rates, and per-node CPU / per-daemon
loop-lag sparklines.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

SPARK = "▁▂▃▄▅▆▇█"

# One batched metrics_query per refresh: (key, series name, fold).
QUERIES = (
    ("serve_qps", "ray_tpu_serve_proxy_requests_total", "rate"),
    ("serve_p99", "ray_tpu_serve_request_phase_seconds", "p99"),
    ("serve_burn", "ray_tpu_serve_slo_burn_rate", "value"),
    ("dag_ticks", "ray_tpu_dag_tick_seconds", "rate"),
    ("dag_recoveries", "ray_tpu_dag_recoveries_total", "value"),
    ("podracer_steps", "ray_tpu_podracer_steps_total", "rate"),
    ("podracer_staleness", "ray_tpu_podracer_weight_staleness", "value"),
    ("store_occupancy", "ray_tpu_store_occupancy_bytes", "value"),
    ("store_spilled", "ray_tpu_store_spilled_bytes", "value"),
    ("pool_hits", "ray_tpu_worker_pool_hits_total", "rate"),
    ("pool_misses", "ray_tpu_worker_pool_misses_total", "rate"),
    ("node_cpu", "ray_tpu_node_cpu_used_frac", "value"),
    ("loop_lag", "ray_tpu_event_loop_lag_seconds", "p95"),
)


def sparkline(points: List[list], width: int = 24) -> str:
    """Unicode sparkline over the last `width` point values."""
    vals = [p[1] for p in points][-width:]
    if not vals:
        return ""
    hi = max(vals)
    if hi <= 0:
        return SPARK[0] * len(vals)
    return "".join(SPARK[min(len(SPARK) - 1,
                             int(v / hi * (len(SPARK) - 1)))] for v in vals)


def _last(points: List[list]) -> Optional[float]:
    return points[-1][1] if points else None


def _fmt(v: Optional[float], unit: str = "", scale: float = 1.0,
         prec: int = 1) -> str:
    if v is None:
        return "-"
    return f"{v * scale:.{prec}f}{unit}"


def fetch(core, window_s: float) -> Dict[str, list]:
    """One batched tsdb query round trip -> {key: series list}."""
    from ray_tpu._private import worker_api
    payload = {"queries": [{"name": name, "fold": fold,
                            "window_s": window_s}
                           for _, name, fold in QUERIES]}
    res = worker_api._call_on_core_loop(
        core, core.gcs.request("metrics_query", payload), 15)
    return {key: series for (key, _, _), series in zip(QUERIES, res)}


def _by_tag(series: List[dict], tag: str,
            where: Optional[dict] = None) -> Dict[str, list]:
    """tag value -> points, filtered by exact `where` tag matches."""
    out: Dict[str, list] = {}
    for s in series or []:
        tags = s.get("tags", {})
        if where and any(tags.get(k) != v for k, v in where.items()):
            continue
        out[tags.get(tag, "")] = s.get("points", [])
    return out


def render(data: Dict[str, list], window_s: float = 300.0,
           width: int = 79) -> str:
    """One frame as a plain string (no ANSI — the caller positions)."""
    lines: List[str] = []
    bar = "─" * width

    def section(title: str):
        lines.append(f"── {title} {bar[:max(0, width - len(title) - 4)]}")

    lines.append(f"ray_tpu top · window {int(window_s)}s · "
                 f"{time.strftime('%H:%M:%S')}")

    section("serve")
    qps = _by_tag(data.get("serve_qps", []), "Deployment")
    p99 = _by_tag(data.get("serve_p99", []), "Deployment",
                  where={"Phase": "total"})
    burn = _by_tag(data.get("serve_burn", []), "Deployment",
                   where={"Window": "fast"})
    deployments = sorted(set(qps) | set(p99) | set(burn))
    if deployments:
        lines.append(f"  {'deployment':<20}{'qps':>8}{'p99 ms':>10}"
                     f"{'burn':>7}  trend")
        for d in deployments:
            lines.append(
                f"  {d:<20.20}{_fmt(_last(qps.get(d, []))):>8}"
                f"{_fmt(_last(p99.get(d, [])), scale=1e3):>10}"
                f"{_fmt(_last(burn.get(d, []))):>7}"
                f"  {sparkline(qps.get(d, []))}")
    else:
        lines.append("  (no serve traffic)")

    section("compiled DAGs")
    ticks = (data.get("dag_ticks") or [{}])[0].get("points", [])
    recov = _last((data.get("dag_recoveries") or [{}])[0].get("points", []))
    lines.append(f"  ticks/s {_fmt(_last(ticks)):>10}   "
                 f"recoveries {_fmt(recov, prec=0):>5}   "
                 f"{sparkline(ticks)}")

    section("podracer")
    steps = (data.get("podracer_steps") or [{}])[0].get("points", [])
    stale = _last((data.get("podracer_staleness")
                   or [{}])[0].get("points", []))
    lines.append(f"  steps/s {_fmt(_last(steps)):>10}   "
                 f"staleness {_fmt(stale, prec=1):>6}   "
                 f"{sparkline(steps)}")

    section("object plane")
    occ = _by_tag(data.get("store_occupancy", []), "Node")
    spill = _by_tag(data.get("store_spilled", []), "Node")
    for node in sorted(occ) or ["-"]:
        o = _last(occ.get(node, []))
        sp = _last(spill.get(node, []))
        lines.append(f"  node {node:<14.14} occupancy "
                     f"{_fmt(o, ' MB', 1e-6):>10}  spilled "
                     f"{_fmt(sp, ' MB', 1e-6):>10}  "
                     f"{sparkline(occ.get(node, []))}")

    section("warm pools")
    hits = _by_tag(data.get("pool_hits", []), "Node")
    misses = _by_tag(data.get("pool_misses", []), "Node")
    for node in sorted(set(hits) | set(misses)) or ["-"]:
        h = sum(p[1] for p in hits.get(node, [])) if node in hits else 0.0
        m = (sum(p[1] for p in misses.get(node, []))
             if node in misses else 0.0)
        # Ratio of summed per-slot rates == hit fraction over the window
        # (slots are uniform), even though the sums themselves aren't counts.
        ratio = h / (h + m) if (h + m) > 0 else None
        lines.append(f"  node {node:<14.14} hit rate "
                     f"{_fmt(ratio, '%', 100.0, 0):>6}")

    section("nodes")
    cpu = _by_tag(data.get("node_cpu", []), "Node")
    for node in sorted(cpu) or ["-"]:
        pts = cpu.get(node, [])
        lines.append(f"  node {node:<14.14} cpu "
                     f"{_fmt(_last(pts), '%', 100.0, 0):>5}  "
                     f"{sparkline(pts)}")
    lag = _by_tag(data.get("loop_lag", []), "Process")
    for proc in sorted(lag):
        pts = lag[proc]
        lines.append(f"  lag  {proc:<14.14} p95 "
                     f"{_fmt(_last(pts), ' ms', 1e3):>9}  "
                     f"{sparkline(pts)}")
    return "\n".join(lines)


def run(args) -> None:
    """CLI entry: connect once, then poll-and-repaint (or print once)."""
    import ray_tpu
    from ray_tpu._private import worker_api
    from ray_tpu.scripts.cli import _address
    ray_tpu.init(address=_address(args))
    core = worker_api.get_core()
    try:
        if args.once:
            print(render(fetch(core, args.window), args.window))
            return
        while True:
            frame = render(fetch(core, args.window), args.window)
            # Plain ANSI repaint: home + clear-below, no curses.
            sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        ray_tpu.shutdown()
