// Minimal pickle codec for the ray_tpu C++ client.
//
// The ray_tpu wire protocol frames pickled plain data (rpc.py:102
// pickle.dumps([kind, msg_id, method, payload], protocol=5)). A non-Python
// client therefore needs to read and write the *plain-data subset* of
// pickle: None, bool, int, float, bytes, str, list, tuple, dict.
//
// ENCODER emits protocol-3 opcodes (every CPython accepts them).
// DECODER handles what CPython's protocol-5 pickler emits for plain data
// (FRAME/MEMOIZE/SHORT_BINUNICODE/...). Anything beyond the plain-data
// subset (classes, reducers) raises — by design: cross-language payloads
// are data, not code (reference: the language-independent msgpack layer
// in src/ray/common/serialization.h plays this role for the reference's
// C++ worker).

#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace raytpu {

// ---------------------------------------------------------------- value

struct PyValue;
using PyValuePtr = std::shared_ptr<PyValue>;

struct PyValue {
  enum class Kind { None, Bool, Int, Float, Bytes, Str, List, Tuple, Dict };
  Kind kind = Kind::None;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;  // Bytes and Str payloads
  std::vector<PyValuePtr> items;                      // List / Tuple
  std::vector<std::pair<PyValuePtr, PyValuePtr>> kv;  // Dict

  static PyValuePtr none() { return std::make_shared<PyValue>(); }
  static PyValuePtr boolean(bool v) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Bool; p->b = v; return p;
  }
  static PyValuePtr integer(int64_t v) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Int; p->i = v; return p;
  }
  static PyValuePtr real(double v) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Float; p->f = v; return p;
  }
  static PyValuePtr bytes(std::string v) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Bytes; p->s = std::move(v); return p;
  }
  static PyValuePtr str(std::string v) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Str; p->s = std::move(v); return p;
  }
  static PyValuePtr list(std::vector<PyValuePtr> v = {}) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::List; p->items = std::move(v); return p;
  }
  static PyValuePtr tuple(std::vector<PyValuePtr> v = {}) {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Tuple; p->items = std::move(v); return p;
  }
  static PyValuePtr dict() {
    auto p = std::make_shared<PyValue>();
    p->kind = Kind::Dict; return p;
  }

  void set(const std::string& key, PyValuePtr v) {
    kv.emplace_back(str(key), std::move(v));
  }
  // Dict lookup by string key; nullptr when missing.
  PyValuePtr get(const std::string& key) const {
    for (const auto& [k, v] : kv)
      if (k->kind == Kind::Str && k->s == key) return v;
    return nullptr;
  }
};

// ---------------------------------------------------------------- encode

class PickleEncoder {
 public:
  static std::string dumps(const PyValuePtr& v) {
    PickleEncoder e;
    e.out_.push_back('\x80');  // PROTO
    e.out_.push_back('\x03');
    e.emit(v);
    e.out_.push_back('.');     // STOP
    return e.out_;
  }

 private:
  std::string out_;

  void raw(const void* p, size_t n) {
    out_.append(reinterpret_cast<const char*>(p), n);
  }
  void u32le(uint32_t v) { raw(&v, 4); }  // little-endian hosts only

  void emit(const PyValuePtr& v) {
    using K = PyValue::Kind;
    switch (v->kind) {
      case K::None: out_.push_back('N'); break;
      case K::Bool: out_.push_back(v->b ? '\x88' : '\x89'); break;
      case K::Int: {
        // LONG1: length byte + minimal little-endian two's complement.
        uint8_t buf[9];
        int n = 0;
        int64_t x = v->i;
        while (true) {
          buf[n++] = static_cast<uint8_t>(x & 0xff);
          int64_t rest = x >> 8;
          bool done = (rest == 0 && !(buf[n - 1] & 0x80)) ||
                      (rest == -1 && (buf[n - 1] & 0x80));
          if (done || n == 8) { if (!done) buf[n++] = x < 0 ? 0xff : 0x00; break; }
          x = rest;
        }
        out_.push_back('\x8a');
        out_.push_back(static_cast<char>(n));
        raw(buf, n);
        break;
      }
      case K::Float: {
        // BINFLOAT: big-endian IEEE754.
        uint64_t bits;
        std::memcpy(&bits, &v->f, 8);
        uint8_t be[8];
        for (int k = 0; k < 8; k++) be[k] = (bits >> (8 * (7 - k))) & 0xff;
        out_.push_back('G');
        raw(be, 8);
        break;
      }
      case K::Bytes:
        if (v->s.size() < 256) {
          out_.push_back('C');  // SHORT_BINBYTES
          out_.push_back(static_cast<char>(v->s.size()));
        } else {
          out_.push_back('B');  // BINBYTES
          u32le(static_cast<uint32_t>(v->s.size()));
        }
        out_.append(v->s);
        break;
      case K::Str:
        out_.push_back('X');  // BINUNICODE (utf-8 expected)
        u32le(static_cast<uint32_t>(v->s.size()));
        out_.append(v->s);
        break;
      case K::List:
        out_.push_back(']');  // EMPTY_LIST
        if (!v->items.empty()) {
          out_.push_back('(');  // MARK
          for (const auto& it : v->items) emit(it);
          out_.push_back('e');  // APPENDS
        }
        break;
      case K::Tuple:
        if (v->items.empty()) { out_.push_back(')'); break; }
        if (v->items.size() <= 3) {
          for (const auto& it : v->items) emit(it);
          out_.push_back(static_cast<char>('\x85' + v->items.size() - 1));
        } else {
          out_.push_back('(');
          for (const auto& it : v->items) emit(it);
          out_.push_back('t');  // TUPLE
        }
        break;
      case K::Dict:
        out_.push_back('}');  // EMPTY_DICT
        if (!v->kv.empty()) {
          out_.push_back('(');
          for (const auto& [k, val] : v->kv) { emit(k); emit(val); }
          out_.push_back('u');  // SETITEMS
        }
        break;
    }
  }
};

// ---------------------------------------------------------------- decode

class PickleDecoder {
 public:
  static PyValuePtr loads(const std::string& data) {
    PickleDecoder d(data);
    return d.run();
  }

 private:
  const std::string& data_;
  size_t pos_ = 0;
  std::vector<PyValuePtr> stack_;
  std::vector<size_t> marks_;
  std::vector<PyValuePtr> memo_;

  explicit PickleDecoder(const std::string& d) : data_(d) {}

  [[noreturn]] void fail(const std::string& what) {
    throw std::runtime_error("pickle decode: " + what + " at offset " +
                             std::to_string(pos_));
  }
  uint8_t u8() {
    if (pos_ >= data_.size()) fail("truncated");
    return static_cast<uint8_t>(data_[pos_++]);
  }
  std::string take(size_t n) {
    if (pos_ + n > data_.size()) fail("truncated");
    std::string s = data_.substr(pos_, n);
    pos_ += n;
    return s;
  }
  uint32_t u16le() { uint32_t v = u8(); v |= u8() << 8; return v; }
  uint32_t u32() {
    uint32_t v = 0;
    for (int k = 0; k < 4; k++) v |= static_cast<uint32_t>(u8()) << (8 * k);
    return v;
  }
  uint64_t u64() {
    uint64_t v = 0;
    for (int k = 0; k < 8; k++) v |= static_cast<uint64_t>(u8()) << (8 * k);
    return v;
  }
  PyValuePtr pop() {
    if (stack_.empty()) fail("stack underflow");
    auto v = stack_.back();
    stack_.pop_back();
    return v;
  }
  PyValuePtr& top() {
    if (stack_.empty()) fail("stack underflow");
    return stack_.back();
  }
  std::vector<PyValuePtr> pop_to_mark() {
    if (marks_.empty()) fail("no mark");
    size_t m = marks_.back();
    marks_.pop_back();
    std::vector<PyValuePtr> out(stack_.begin() + m, stack_.end());
    stack_.resize(m);
    return out;
  }

  PyValuePtr run() {
    while (true) {
      uint8_t op = u8();
      switch (op) {
        case 0x80: u8(); break;                      // PROTO n
        case 0x95: u64(); break;                     // FRAME len (ignored)
        case '.': {                                  // STOP
          if (stack_.size() != 1) fail("bad final stack");
          return stack_.back();
        }
        case 'N': stack_.push_back(PyValue::none()); break;
        case 0x88: stack_.push_back(PyValue::boolean(true)); break;
        case 0x89: stack_.push_back(PyValue::boolean(false)); break;
        case 'J': {                                  // BININT i32
          int32_t v = static_cast<int32_t>(u32());
          stack_.push_back(PyValue::integer(v));
          break;
        }
        case 'K': stack_.push_back(PyValue::integer(u8())); break;
        case 'M': stack_.push_back(PyValue::integer(u16le())); break;
        case 0x8a: {                                 // LONG1
          int n = u8();
          if (n > 8) fail("LONG1 too wide for int64");
          uint64_t v = 0;
          uint8_t last = 0;
          for (int k = 0; k < n; k++) { last = u8(); v |= static_cast<uint64_t>(last) << (8 * k); }
          if (n > 0 && (last & 0x80))               // sign-extend
            for (int k = n; k < 8; k++) v |= 0xffULL << (8 * k);
          stack_.push_back(PyValue::integer(static_cast<int64_t>(v)));
          break;
        }
        case 'G': {                                  // BINFLOAT (BE)
          uint64_t bits = 0;
          for (int k = 0; k < 8; k++) bits = (bits << 8) | u8();
          double d;
          std::memcpy(&d, &bits, 8);
          stack_.push_back(PyValue::real(d));
          break;
        }
        case 'C': { size_t n = u8(); stack_.push_back(PyValue::bytes(take(n))); break; }
        case 'B': { size_t n = u32(); stack_.push_back(PyValue::bytes(take(n))); break; }
        case 0x8e: { size_t n = u64(); stack_.push_back(PyValue::bytes(take(n))); break; }
        case 0x8c: { size_t n = u8(); stack_.push_back(PyValue::str(take(n))); break; }
        case 'X': { size_t n = u32(); stack_.push_back(PyValue::str(take(n))); break; }
        case 0x8d: { size_t n = u64(); stack_.push_back(PyValue::str(take(n))); break; }
        case ']': stack_.push_back(PyValue::list()); break;
        case ')': stack_.push_back(PyValue::tuple()); break;
        case '}': stack_.push_back(PyValue::dict()); break;
        case '(': marks_.push_back(stack_.size()); break;
        case 'a': {                                  // APPEND
          auto v = pop(); auto& lst = top();
          if (lst->kind != PyValue::Kind::List) fail("APPEND to non-list");
          lst->items.push_back(v);
          break;
        }
        case 'e': {                                  // APPENDS
          auto vals = pop_to_mark(); auto& lst = top();
          if (lst->kind != PyValue::Kind::List) fail("APPENDS to non-list");
          for (auto& v : vals) lst->items.push_back(v);
          break;
        }
        case 's': {                                  // SETITEM
          auto v = pop(); auto k = pop(); auto& d = top();
          if (d->kind != PyValue::Kind::Dict) fail("SETITEM to non-dict");
          d->kv.emplace_back(k, v);
          break;
        }
        case 'u': {                                  // SETITEMS
          auto vals = pop_to_mark(); auto& d = top();
          if (d->kind != PyValue::Kind::Dict) fail("SETITEMS to non-dict");
          for (size_t k = 0; k + 1 < vals.size(); k += 2)
            d->kv.emplace_back(vals[k], vals[k + 1]);
          break;
        }
        case 0x85: case 0x86: case 0x87: {           // TUPLE1/2/3
          int n = op - 0x85 + 1;
          std::vector<PyValuePtr> v(n);
          for (int k = n - 1; k >= 0; k--) v[k] = pop();
          stack_.push_back(PyValue::tuple(std::move(v)));
          break;
        }
        case 't': stack_.push_back(PyValue::tuple(pop_to_mark())); break;
        case 0x94: memo_.push_back(top()); break;           // MEMOIZE
        case 'q': { u8(); memo_.push_back(top()); break; }          // BINPUT
        case 'r': { u32(); memo_.push_back(top()); break; }         // LONG_BINPUT
        case 'h': {                                  // BINGET
          size_t k = u8();
          if (k >= memo_.size()) fail("BINGET out of range");
          stack_.push_back(memo_[k]);
          break;
        }
        case 'j': {                                  // LONG_BINGET
          size_t k = u32();
          if (k >= memo_.size()) fail("LONG_BINGET out of range");
          stack_.push_back(memo_[k]);
          break;
        }
        default:
          fail("unsupported opcode 0x" + std::to_string(op) +
               " (plain-data subset only)");
      }
    }
  }
};

}  // namespace raytpu
