// End-to-end probe for the ray_tpu C++ client (run by
// tests/test_cpp_client.py against a live cluster + client server).
//
//   ./demo <host> <port>
//
// Exercises: connect, Put/Get round-trip of nested plain data, task
// submission by qualified name with value + ref args, Wait, Nodes.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "ray_client.hpp"

using raytpu::PyValue;
using raytpu::RayTpuClient;

int main(int argc, char** argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  try {
    RayTpuClient client(argv[1], std::atoi(argv[2]));
    std::printf("connected job=%s\n", client.job_id().c_str());

    // Put/Get round trip of nested plain data.
    auto value = PyValue::dict();
    value->set("name", PyValue::str("cpp"));
    value->set("xs", PyValue::list({PyValue::integer(1),
                                    PyValue::integer(2),
                                    PyValue::integer(3)}));
    value->set("pi", PyValue::real(3.25));
    value->set("blob", PyValue::bytes(std::string("\x00\x01\x02", 3)));
    auto ref = client.Put(value);
    auto back = client.Get(ref);
    if (back->get("name")->s != "cpp") return 1;
    if (back->get("xs")->items.size() != 3) return 1;
    if (back->get("xs")->items[2]->i != 3) return 1;
    if (back->get("pi")->f != 3.25) return 1;
    if (back->get("blob")->s.size() != 3) return 1;
    std::printf("put/get ok\n");

    // Cross-language task: plain args.
    auto sum_ref = client.Submit(
        "cpp_targets:add_all",
        {PyValue::list({PyValue::integer(10), PyValue::integer(20),
                        PyValue::integer(12)})});
    auto total = client.Get(sum_ref);
    if (total->i != 42) return 1;
    std::printf("task by name ok: %lld\n",
                static_cast<long long>(total->i));

    // Ref arg: pass the stored dict to a Python function.
    auto describe_ref = client.Submit("cpp_targets:describe", {}, {ref});
    auto desc = client.Get(describe_ref);
    if (desc->s.find("cpp") == std::string::npos) return 1;
    std::printf("ref arg ok: %s\n", desc->s.c_str());

    // Wait on a slow task.
    auto slow = client.Submit("cpp_targets:slow_echo",
                              {PyValue::real(0.2), PyValue::str("done")});
    if (client.Wait({slow}, 1, 30.0) != 1) return 1;
    if (client.Get(slow)->s != "done") return 1;
    std::printf("wait ok\n");


    // Cross-language actor: create a Python class by descriptor, call
    // methods (value + ref args), look it up by name, kill it.
    auto actor = client.CreateActor(
        "cpp_targets:Counter", {PyValue::integer(100)}, "cpp-counter");
    auto r1 = client.CallActor(actor, "add", {PyValue::integer(5)});
    if (client.Get(r1)->i != 105) return 1;
    auto r2 = client.CallActor(actor, "add", {PyValue::integer(7)});
    if (client.Get(r2)->i != 112) return 1;
    auto found = client.GetNamedActor("cpp-counter");
    if (found.id != actor.id) return 1;
    auto r3 = client.CallActor(found, "get");
    if (client.Get(r3)->i != 112) return 1;
    client.KillActor(actor);
    std::printf("actor ok\n");

    // Cluster view.
    auto nodes = client.Nodes();
    if (nodes->kind != PyValue::Kind::List || nodes->items.empty()) return 1;
    std::printf("nodes=%zu\n", nodes->items.size());

    std::printf("CPP-CLIENT-OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAILED: %s\n", e.what());
    return 1;
  }
}
