// ray_tpu C++ client: a native driver for a ray_tpu cluster.
//
// Reference parity: cpp/ (the reference's C++ worker API — ray::Init,
// ray::Put/Get/Wait, ray::Task(...).Remote()). Here the C++ process is a
// remote DRIVER speaking the client-server protocol
// (ray_tpu/util/client/server.py) over one TCP connection, the same
// surface the ray_tpu:// Python client uses:
//   * framing: 4-byte LE length + pickle([kind, msg_id, method, payload])
//     (ray_tpu/_private/rpc.py:93-104)
//   * values: "RTPU"-magic buffer wrap around a pickled plain-data body
//     (ray_tpu/_private/serialization.py:126-160)
//   * tasks: cross-language submission by "module:function" name
//     (rpc_submit_named — the reference's cross_language descriptor path).
//
// Synchronous, single-connection, plain-data args/results. Compile with:
//   g++ -std=c++17 -O2 demo_client.cpp -o demo

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <random>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "pickle_codec.hpp"

namespace raytpu {

// rpc.py:24 — REQUEST, RESPONSE, ERROR, NOTIFY, PUSH
enum MsgKind { kRequest = 0, kResponse = 1, kError = 2, kNotify = 3,
               kPush = 4 };

struct ObjectRef {
  std::string id;     // binary object id
  std::string owner;  // owner address
};

class RayTpuClient {
 public:
  RayTpuClient(const std::string& host, int port) {
    dial(host, port);
    session_ = random_hex(32);
    auto reply = request("client_connect", PyValue::dict());
    auto job = reply->get("job_id");
    if (!job) throw std::runtime_error("connect: no job id");
    job_id_ = job->s;
  }

  ~RayTpuClient() {
    try {
      request("client_disconnect", PyValue::dict());
    } catch (...) {}
    if (fd_ >= 0) ::close(fd_);
  }

  const std::string& job_id() const { return job_id_; }

  // ---- object store ------------------------------------------------

  ObjectRef Put(const PyValuePtr& value) {
    auto payload = PyValue::dict();
    payload->set("data", PyValue::bytes(wrap_value(value)));
    auto reply = request("client_put", payload);
    return ref_of(reply);
  }

  PyValuePtr Get(const ObjectRef& ref, double timeout_s = 60.0) {
    auto payload = PyValue::dict();
    auto refs = PyValue::list();
    refs->items.push_back(PyValue::bytes(ref.id));
    payload->set("refs", refs);
    payload->set("timeout", PyValue::real(timeout_s));
    auto reply = request("client_get", payload);
    if (reply->kind == PyValue::Kind::Dict && reply->get("__client_error__"))
      throw std::runtime_error("remote task failed (see server logs)");
    if (reply->kind != PyValue::Kind::List || reply->items.empty())
      throw std::runtime_error("get: bad reply");
    return unwrap_value(reply->items[0]->s);
  }

  // ready-count after waiting up to timeout (client_wait).
  size_t Wait(const std::vector<ObjectRef>& refs, size_t num_returns,
              double timeout_s) {
    auto payload = PyValue::dict();
    auto lst = PyValue::list();
    for (const auto& r : refs) lst->items.push_back(PyValue::bytes(r.id));
    payload->set("refs", lst);
    payload->set("num_returns",
                 PyValue::integer(static_cast<int64_t>(num_returns)));
    payload->set("timeout", PyValue::real(timeout_s));
    auto reply = request("client_wait", payload);
    if (reply->kind == PyValue::Kind::Dict && reply->get("__client_error__"))
      throw std::runtime_error("wait failed server-side (see server logs)");
    if (reply->kind != PyValue::Kind::Tuple || reply->items.size() != 2)
      throw std::runtime_error("wait: bad reply");
    return reply->items[0]->items.size();
  }

  // ---- tasks -------------------------------------------------------

  // Submit an importable Python function by "module:function" name.
  // Args are plain data or ObjectRefs.
  ObjectRef Submit(const std::string& qualname,
                   const std::vector<PyValuePtr>& args,
                   const std::vector<ObjectRef>& ref_args = {}) {
    auto payload = PyValue::dict();
    payload->set("func", PyValue::str(qualname));
    payload->set("args", tagged_args(args, ref_args));
    payload->set("num_returns", PyValue::integer(1));
    auto reply = request("client_submit_named", payload);
    if (reply->kind != PyValue::Kind::List || reply->items.empty())
      throw std::runtime_error("submit: bad reply");
    return ref_of(reply->items[0]);
  }

  // ---- actors ------------------------------------------------------
  // Cross-language actor lifecycle (reference: cpp/include/ray/api.h
  // ray::Actor(...).Remote() + cross_language.py): the class is an
  // importable Python "module:Class" descriptor; this driver creates it,
  // calls methods, and kills it over the client protocol.

  struct ActorHandle {
    std::string id;  // binary actor id
  };

  ActorHandle CreateActor(const std::string& class_path,
                          const std::vector<PyValuePtr>& args = {},
                          const std::string& name = "") {
    auto payload = PyValue::dict();
    payload->set("class_path", PyValue::str(class_path));
    payload->set("args", tagged_args(args, {}));
    if (!name.empty()) payload->set("name", PyValue::str(name));
    auto reply = request("client_create_actor", payload);
    if (reply->kind != PyValue::Kind::Bytes)
      throw std::runtime_error("create_actor: bad reply");
    return ActorHandle{reply->s};
  }

  ObjectRef CallActor(const ActorHandle& actor, const std::string& method,
                      const std::vector<PyValuePtr>& args = {},
                      const std::vector<ObjectRef>& ref_args = {}) {
    auto payload = PyValue::dict();
    payload->set("actor_id", PyValue::bytes(actor.id));
    payload->set("method", PyValue::str(method));
    payload->set("args", tagged_args(args, ref_args));
    payload->set("num_returns", PyValue::integer(1));
    auto reply = request("client_submit_actor_task", payload);
    if (reply->kind != PyValue::Kind::List || reply->items.empty())
      throw std::runtime_error("call_actor: bad reply");
    return ref_of(reply->items[0]);
  }

  void KillActor(const ActorHandle& actor) {
    auto payload = PyValue::dict();
    payload->set("actor_id", PyValue::bytes(actor.id));
    request("client_kill_actor", payload);
  }

  ActorHandle GetNamedActor(const std::string& name) {
    auto payload = PyValue::dict();
    payload->set("name", PyValue::str(name));
    auto reply = request("client_get_named_actor", payload);
    if (reply->kind != PyValue::Kind::Bytes)
      throw std::runtime_error("get_named_actor: bad reply");
    return ActorHandle{reply->s};
  }

  // ---- cluster -----------------------------------------------------

  PyValuePtr Nodes() { return request("client_nodes", PyValue::dict()); }

  // ---- protocol internals (public for tests) -----------------------

  // [("val", wrapped-bytes) | ("ref", id-bytes)] argument list, the
  // client-server protocol's tagged-arg shape (server.py _args_of).
  PyValuePtr tagged_args(const std::vector<PyValuePtr>& args,
                         const std::vector<ObjectRef>& ref_args) {
    auto tagged = PyValue::list();
    for (const auto& a : args) {
      tagged->items.push_back(PyValue::tuple(
          {PyValue::str("val"), PyValue::bytes(wrap_value(a))}));
    }
    for (const auto& r : ref_args) {
      tagged->items.push_back(PyValue::tuple(
          {PyValue::str("ref"), PyValue::bytes(r.id)}));
    }
    return tagged;
  }

  PyValuePtr request(const std::string& method, PyValuePtr payload) {
    payload->set("session", PyValue::str(session_));
    int64_t msg_id = next_id_++;
    auto frame = PyValue::list({PyValue::integer(kRequest),
                                PyValue::integer(msg_id),
                                PyValue::str(method), payload});
    send_frame(PickleEncoder::dumps(frame));
    while (true) {
      auto msg = PickleDecoder::loads(recv_frame());
      if (msg->kind != PyValue::Kind::List || msg->items.size() != 4)
        throw std::runtime_error("bad frame");
      int64_t kind = msg->items[0]->i;
      if (kind == kPush || kind == kNotify) continue;  // not subscribed
      if (msg->items[1]->i != msg_id) continue;        // stale reply
      if (kind == kError) {
        const auto& err = msg->items[3];
        std::string what = "rpc error";
        if (err->kind == PyValue::Kind::Tuple && err->items.size() >= 3)
          what = err->items[1]->s + ": " + err->items[2]->s;
        throw std::runtime_error(what);
      }
      return msg->items[3];
    }
  }

  // serialization.py value wrap: MAGIC u32 | n u32 | sizes u64[n] | pad8
  // | buffers (single in-band pickle buffer from this client).
  static std::string wrap_value(const PyValuePtr& v) {
    std::string body = PickleEncoder::dumps(v);
    size_t header = 8 + 8;
    size_t off = pad8(header);
    std::string out(off + body.size(), '\0');
    uint32_t magic = 0x52545055, n = 1;
    uint64_t sz = body.size();
    std::memcpy(&out[0], &magic, 4);
    std::memcpy(&out[4], &n, 4);
    std::memcpy(&out[8], &sz, 8);
    std::memcpy(&out[off], body.data(), body.size());
    return out;
  }

  static PyValuePtr unwrap_value(const std::string& data) {
    if (data.size() < 16) throw std::runtime_error("value too short");
    uint32_t magic, n;
    std::memcpy(&magic, &data[0], 4);
    std::memcpy(&n, &data[4], 4);
    if (magic != 0x52545055) throw std::runtime_error("bad value magic");
    if (n != 1)
      throw std::runtime_error(
          "value uses out-of-band buffers (not plain data)");
    uint64_t sz;
    std::memcpy(&sz, &data[8], 8);
    size_t off = pad8(8 + 8);
    return PickleDecoder::loads(data.substr(off, sz));
  }

 private:
  int fd_ = -1;
  int64_t next_id_ = 1;
  std::string session_;
  std::string job_id_;

  static size_t pad8(size_t n) { return (n + 7) / 8 * 8; }

  static std::string random_hex(size_t n) {
    // Full-entropy session id: draw from random_device per nibble-pair
    // and fold in pid + clock (a 32-bit-seeded PRNG would cap the id
    // space at 2^32 and a collision cross-wires two client sessions).
    static const char* hex = "0123456789abcdef";
    std::random_device rd;
    uint64_t mix = static_cast<uint64_t>(::getpid()) ^
                   static_cast<uint64_t>(
                       std::chrono::steady_clock::now()
                           .time_since_epoch().count());
    std::string s;
    for (size_t k = 0; k < n; k++) {
      uint32_t r = rd() ^ static_cast<uint32_t>(mix >> ((k % 8) * 8));
      s.push_back(hex[r % 16]);
    }
    return s;
  }

  static ObjectRef ref_of(const PyValuePtr& pair) {
    if (pair->kind != PyValue::Kind::Tuple || pair->items.size() != 2)
      throw std::runtime_error("bad ref reply");
    return ObjectRef{pair->items[0]->s, pair->items[1]->s};
  }

  void dial(const std::string& host, int port) {
    struct addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 || res == nullptr)
      throw std::runtime_error("resolve failed: " + host);
    fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0 || ::connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      freeaddrinfo(res);
      throw std::runtime_error("connect failed: " + host + ":" +
                               std::to_string(port));
    }
    freeaddrinfo(res);
  }

  void send_all(const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    while (n) {
      ssize_t w = ::send(fd_, c, n, 0);
      if (w <= 0) throw std::runtime_error("send failed");
      c += w;
      n -= static_cast<size_t>(w);
    }
  }
  void recv_all(void* p, size_t n) {
    char* c = static_cast<char*>(p);
    while (n) {
      ssize_t r = ::recv(fd_, c, n, 0);
      if (r <= 0) throw std::runtime_error("connection lost");
      c += r;
      n -= static_cast<size_t>(r);
    }
  }
  void send_frame(const std::string& body) {
    uint32_t len = static_cast<uint32_t>(body.size());
    send_all(&len, 4);
    send_all(body.data(), body.size());
  }
  std::string recv_frame() {
    uint32_t len = 0;
    recv_all(&len, 4);
    std::string body(len, '\0');
    recv_all(&body[0], len);
    return body;
  }
};

}  // namespace raytpu
