"""Podracer runtime: the act->learn data path as a compiled DAG.

One tick of the substrate:

  driver --(tick, weight_version, weights_ref)--> every rollout actor
      --(fixed-shape trajectory batch over a ring/store channel)-->
  learner --(version, new weights_ref, metrics)--> driver

The whole path is a `tick_replay=True` compiled DAG (PR 12/13): zero
per-tick task RPCs, bounded pipelining (channel depth = how stale actor
weights may run), and self-healing — a slice preemption mid-rollout
migrates the affected gang uncharged (`preempted_restarts`) while the
driver's replay buffer + per-message tick sequence give exactly-once
batch delivery (the learner applies every tick exactly once, asserted
via its `applied` counter riding each output).

Weight broadcast rides the shm plane: the learner emits new params
(numpy leaves) once per `broadcast_interval` updates; the driver folds
them into the control tuple, so ONE input-ring write serves every
actor gang — params land as pickle-5 out-of-band buffers and each
actor reads a ZERO-COPY view of the same slot (copied once into its
runner, since the ring recycles slots `depth` ticks later). Params
above the plane's weights threshold are put into the object store ONCE
PER VERSION by the driver (PlaneRef in the control tuple) — per-tick
submits ring only the tiny ref, and actors fetch the tree (zero-copy
view) only when the version actually advanced; the channels' own
oversize path remains the backstop for anything else that outgrows a
slot. Versions
observed by any actor are monotonic — a restarted actor re-adopts the
current weights from its first control tuple, and a restarted learner
resumes the version sequence from the control echo (its weights re-
initialize unless a checkpoint layer restores them — see ROADMAP).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

import numpy as np

from ray_tpu.podracer.topology import (PodracerConfig, TopologyPlan,
                                       TopologyPlanner)

_metrics = None


def _metric_handles() -> dict:
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics
        _metrics = {
            "steps": metrics.Counter(
                "ray_tpu_podracer_steps_total",
                "environment steps collected by podracer actor gangs"),
            "batches": metrics.Counter(
                "ray_tpu_podracer_batches_total",
                "trajectory batches delivered act->learn (exactly once "
                "per actor per tick)"),
            "staleness": metrics.Gauge(
                "ray_tpu_podracer_weight_staleness",
                "learner weight version minus the oldest version any "
                "actor sampled with, at the last collected tick"),
        }
    return _metrics


def _to_numpy_tree(params):
    import jax
    return jax.tree_util.tree_map(np.asarray, params)


class _RolloutWorker:
    """One actor-gang member: wraps an rllib EnvRunner; `collect` is the
    compiled-DAG node method (fixed-shape fragments per tick)."""

    # The columns a PPO learner consumes — everything else the sampler
    # produces stays host-local so the channel message shape is fixed
    # and minimal.
    _COLS = ("obs", "actions", "action_logp", "advantages",
             "value_targets")

    def __init__(self, env_spec, env_config: dict, num_envs: int,
                 fragment_len: int, seed: int, hidden=(32, 32),
                 gamma: float = 0.99, lam: float = 0.95):
        from ray_tpu.rllib.env_runner import EnvRunner
        self._runner = EnvRunner(env_spec, env_config, num_envs, seed,
                                 hidden=tuple(hidden))
        self._fragment_len = int(fragment_len)
        self._gamma = float(gamma)
        self._lam = float(lam)
        self._version = 0
        # Bounded: one entry per collect on a loop that ticks forever.
        self._versions_seen: deque = deque(maxlen=4096)

    def collect(self, ctl) -> dict:
        """One rollout fragment under the weights `ctl` announces.
        ctl = (tick, weight_version, weights) — `weights` deserialized
        as zero-copy views onto the input ring slot every actor gang
        shares (one write, N readers). Oversize trees arrive as a
        PlaneRef into the node's object store instead: resolved (one
        zero-copy get) ONLY when the version actually advanced — stale
        ticks skip the fetch entirely."""
        import jax
        tick, version, weights = ctl
        if weights is not None and version > self._version:
            from ray_tpu._private import object_plane
            weights = object_plane.resolve(weights)
            # Copy out of the ring slot / store view ONCE per broadcast:
            # the stored params outlive this tick, and the writer
            # recycles the slot `depth` messages later.
            self._runner.set_weights(
                jax.tree_util.tree_map(np.array, weights))
            self._version = version
        self._versions_seen.append(self._version)
        batch = self._runner.sample(self._fragment_len, self._gamma,
                                    self._lam)
        return {
            "tick": tick,
            "version": self._version,
            "ctl_version": version,
            "steps": self._fragment_len * len(self._runner._envs),
            "rewards": self._runner.episode_rewards(),
            "columns": {k: np.asarray(batch[k]) for k in self._COLS},
        }

    def versions_seen(self) -> List[int]:
        """Recent weight versions at each collect, in order (test
        probe: must be monotonic — non-decreasing — across
        migrations)."""
        return list(self._versions_seen)

    def ping(self):
        return True


class _Learner:
    """The learner gang's single rep: consumes every gang's batch each
    tick, runs the jitted PPO update, broadcasts weights on a versioned
    cadence via the object plane."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float,
                 hidden=(32, 32), minibatch_size: int = 64,
                 num_epochs: int = 1, broadcast_interval: int = 1,
                 seed: int = 0):
        from ray_tpu.rllib.learner import PPOLearner
        self._learner = PPOLearner(obs_dim, num_actions, lr=lr,
                                   hidden=tuple(hidden), seed=seed)
        self._minibatch_size = int(minibatch_size)
        self._num_epochs = int(num_epochs)
        self._broadcast_interval = max(1, int(broadcast_interval))
        self._seed = seed
        self._version = 0
        self._weights = None
        self._applied = 0
        self._broadcast()

    def _broadcast(self):
        """Stamp a new version; the numpy param tree rides the output
        channel to the driver, which folds it into the NEXT control
        tuple — one input-ring write then serves every actor."""
        self._version += 1
        self._weights = _to_numpy_tree(self._learner.get_weights())

    def control(self) -> tuple:
        """(version, weights) for the driver's first control tuple."""
        return (self._version, self._weights)

    def learn(self, *batches) -> dict:
        from ray_tpu.rllib import sample_batch as sb
        # Restart resumption: a migrated/restarted learner holds fresh
        # params, but the control echo names the live version sequence —
        # resume it so versions observed downstream stay monotonic (the
        # params themselves re-initialize; restoring them is the
        # checkpoint layer's job, see ROADMAP).
        ctl_version = max(b["ctl_version"] for b in batches)
        if ctl_version > self._version:
            self._version = ctl_version
            self._weights = _to_numpy_tree(self._learner.get_weights())
        cols = {k: np.concatenate([b["columns"][k] for b in batches])
                for k in batches[0]["columns"]}
        train = sb.SampleBatch(cols)
        metrics = self._learner.update(
            train, minibatch_size=min(self._minibatch_size,
                                      len(train)) or 1,
            num_epochs=self._num_epochs,
            seed=self._seed + self._applied)
        self._applied += 1
        broadcast = self._applied % self._broadcast_interval == 0
        if broadcast:
            self._broadcast()
        tick = batches[0]["tick"]
        return {
            "tick": tick,
            # Exactly-once probe: applied must equal tick+1 at every
            # collected output — a replayed tick that re-ran the update
            # (lost dedupe) or a dropped batch both break the equality.
            "applied": self._applied,
            "tick_skew": sum(1 for b in batches if b["tick"] != tick),
            "version": self._version,
            # Params ride the output only when the version bumped (the
            # recovery-armed loop caches recent outputs as wire bytes —
            # shipping the tree every tick would multiply that memory).
            "weights": self._weights if broadcast else None,
            # Per-actor weight versions at sample time, in actor order —
            # the driver-side monotonicity probe (and staleness source).
            "versions": [b["version"] for b in batches],
            "staleness": self._version - min(b["version"] for b in batches),
            "num_batches": len(batches),
            "steps": int(sum(b["steps"] for b in batches)),
            "rewards": [r for b in batches for r in b["rewards"]],
            "metrics": {k: float(v) for k, v in metrics.items()},
        }

    def ping(self):
        return True


def _probe_env_dims(env_spec, env_config: dict) -> tuple:
    from ray_tpu.rllib.env import make_env
    env = make_env(env_spec, env_config)
    return env.observation_dim, env.num_actions


class PodracerRun:
    """Driver handle: compile once, tick forever (teardown() releases
    the DAG, the gang actors, and the plan's slice reservations)."""

    def __init__(self, config: PodracerConfig,
                 plan: Optional[TopologyPlan] = None):
        import ray_tpu
        from ray_tpu.dag import InputNode
        from ray_tpu.dag.compiled import CompiledDAG
        from ray_tpu.rllib.env import get_env_creator

        self.config = config
        # Teardown-relevant state FIRST: any failure mid-__init__ (a
        # constructor timeout, a compile error) must release whatever
        # was already acquired — actors, the learner, the plan's slice
        # reservations — instead of leaking max_restarts=-1 actors.
        self._torn_down = False
        self.plan = None
        self.actors: List[Any] = []
        self.learner = None
        self.dag = None
        self._pending: deque = deque()
        self.ticks = 0
        self.steps = 0
        # Bounded histories: the driver ticks forever; stats() and the
        # test probes only ever need a recent window.
        self.episode_rewards: deque = deque(maxlen=1000)
        self.outputs: deque = deque(maxlen=4096)
        self._submit_lock = threading.Lock()
        # Control-tuple form of the current weights: literal tree when
        # small, PlaneRef when oversize (one store put per VERSION, not
        # per tick — the old path re-spilled the whole tree into the
        # channel's oversize store put every submit). Recent refs stay
        # held so pipelined in-flight ticks can't race the free.
        self._ctl_weights = None
        self._weight_refs: deque = deque(maxlen=8)
        try:
            self._build(config, plan)
        except BaseException:
            self.teardown()
            raise

    def _build(self, config: PodracerConfig,
               plan: Optional[TopologyPlan]):
        import ray_tpu
        from ray_tpu.dag import InputNode
        from ray_tpu.dag.compiled import CompiledDAG
        from ray_tpu.rllib.env import get_env_creator

        t0 = time.time()
        self.plan = plan or TopologyPlanner(config).plan()
        creator = get_env_creator(config.env)
        obs_dim, num_actions = _probe_env_dims(creator, config.env_config)

        actor_cls = ray_tpu.remote(num_cpus=config.actor_num_cpus)(
            _RolloutWorker)
        for g, gang in enumerate(self.plan.actor_gangs):
            for m in range(config.actors_per_gang):
                opts = dict(gang.member_options[m]
                            if m < len(gang.member_options) else {})
                opts["max_restarts"] = -1
                self.actors.append(actor_cls.options(**opts).remote(
                    creator, config.env_config, config.num_envs,
                    config.fragment_len,
                    seed=config.seed + 1000 * (len(self.actors) + 1),
                    hidden=config.hidden, gamma=config.gamma,
                    lam=config.lam))
        learner_cls = ray_tpu.remote(num_cpus=config.learner_num_cpus)(
            _Learner)
        lopts = dict(self.plan.learner.member_options[0]
                     if self.plan.learner.member_options else {})
        lopts["max_restarts"] = -1
        self.learner = learner_cls.options(**lopts).remote(
            obs_dim, num_actions, lr=config.lr, hidden=config.hidden,
            minibatch_size=config.minibatch_size,
            num_epochs=config.num_epochs,
            broadcast_interval=config.broadcast_interval,
            seed=config.seed)

        # Bootstrap: actors start from the learner's version-1 weights
        # (constructor broadcast), so every gang samples the same policy
        # from tick 0.
        self._version, self._weights = ray_tpu.get(
            self.learner.control.remote(), timeout=120)
        self._ctl_weights = self._fold_weights(self._weights)
        ray_tpu.get([a.ping.remote() for a in self.actors], timeout=120)

        with InputNode() as inp:
            root = self.learner.learn.bind(
                *[a.collect.bind(inp) for a in self.actors])
        # patient_readers: every node here computes for milliseconds per
        # tick (rollout / learn), so blocked channel readers must nap,
        # not hot-poll — polling peers starve the computing process
        # wherever pipeline participants outnumber cores.
        self.dag = CompiledDAG.compile(
            root, channel_depth=config.channel_depth,
            max_message_size=config.max_message_size, tick_replay=True,
            patient_readers=True)
        self._export_span("podracer:compile", t0, time.time())

    def _fold_weights(self, weights):
        """Route a weight tree into the control tuple: literal below the
        plane's weights threshold, else ONE object-plane put for this
        version with only the ref ringing to every actor gang."""
        if weights is None:
            return None
        from ray_tpu._private import object_plane
        try:
            import jax
            size = sum(int(np.asarray(leaf).nbytes)
                       for leaf in jax.tree_util.tree_leaves(weights))
        except Exception:  # noqa: BLE001 — unsized tree: send literal
            return weights
        if size < object_plane.threshold("weights"):
            return weights
        ref = object_plane.put_object(weights)
        self._weight_refs.append(ref)
        return object_plane.PlaneRef(ref)

    # -- ticking -------------------------------------------------------
    def submit(self):
        """Submit one tick (pipelined up to channel_depth by the DAG's
        input-write backpressure); pair with collect(). The control
        tuple carries the CURRENT weights every tick — one multi-reader
        ring write serves every actor gang zero-copy, and a freshly
        restarted actor re-adopts the live version from its first
        message instead of sampling with init params."""
        # One lock serializes driver-side submitters so the tick
        # embedded in the control tuple cannot desync from the sequence
        # the DAG assigns the write (two racing readers of _next_seq
        # would both stamp N while the DAG hands out N and N+1 — the
        # learner's applied==tick+1 probe would report phantom losses).
        with self._submit_lock:
            ref = self.dag.execute_async(
                (self.dag._next_seq, self._version, self._ctl_weights))
            self._pending.append((ref, time.time()))
        return ref

    def collect(self, timeout: Optional[float] = None) -> dict:
        """Collect the oldest in-flight tick's learner output; folds the
        new weight version into the next control tuple and the podracer
        metrics."""
        ref, t0 = self._pending.popleft()
        out = ref.result(timeout)
        if out["version"] > self._version and out["weights"] is not None:
            self._version, self._weights = out["version"], out["weights"]
            self._ctl_weights = self._fold_weights(self._weights)
            self._export_span("podracer:broadcast", t0, time.time(),
                              only_if_traced=True)
        self.ticks += 1
        self.steps += out["steps"]
        self.episode_rewards.extend(out["rewards"])
        # Keep the tick record without the param tree (a long run must
        # not accumulate one weights copy per broadcast).
        self.outputs.append({k: v for k, v in out.items()
                             if k != "weights"})
        try:
            m = _metric_handles()
            m["steps"].inc(out["steps"])
            m["batches"].inc(out["num_batches"])
            m["staleness"].set(float(out["staleness"]))
        except Exception:  # noqa: BLE001 — metrics never block ticks
            pass
        self._export_span("podracer:tick", t0, time.time(),
                          only_if_traced=True)
        return out

    def step(self, timeout: Optional[float] = None) -> dict:
        """One synchronous tick: submit + collect."""
        self.submit()
        return self.collect(timeout)

    def run(self, num_ticks: int, window: Optional[int] = None,
            timeout: Optional[float] = None) -> List[dict]:
        """Windowed pipelined ticking (the StagePipeline pattern): keep
        up to `window` ticks in flight, collect in submission order."""
        window = max(1, window or self.config.channel_depth)
        out: List[dict] = []
        for _ in range(num_ticks):
            if len(self._pending) >= window:
                out.append(self.collect(timeout))
            self.submit()
        while self._pending:
            out.append(self.collect(timeout))
        return out

    # -- introspection -------------------------------------------------
    def stats(self) -> dict:
        d = self.dag.stats()
        return {
            "mode": self.plan.mode, "ticks": self.ticks,
            "steps": self.steps, "weight_version": self._version,
            "inflight": len(self._pending),
            "max_inflight": d["max_inflight"],
            "recoveries": d["recoveries"],
            "replayed_ticks": d["replayed_ticks"],
            "dag_state": d["state"],
            "staleness": (self.outputs[-1]["staleness"]
                          if self.outputs else 0),
            "episode_reward_mean": (
                float(np.mean(list(self.episode_rewards)[-100:]))
                if self.episode_rewards else float("nan")),
        }

    # -- teardown ------------------------------------------------------
    def teardown(self):
        """Release everything — safe from ANY partial-__init__ state
        (the failure path calls this before the caller ever holds a
        handle)."""
        if getattr(self, "_torn_down", True):
            return
        self._torn_down = True
        import ray_tpu
        try:
            if self.dag is not None:
                self.dag.teardown()
        finally:
            for a in self.actors + [self.learner]:
                if a is None:
                    continue
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001 — already gone
                    pass
            if self.plan is not None:
                self.plan.teardown()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass

    @staticmethod
    def _export_span(name: str, start: float, end: float,
                     only_if_traced: bool = False):
        try:
            from ray_tpu.util import tracing
            if only_if_traced and not tracing.is_enabled():
                return
            from ray_tpu._private import flightrec
            tracing.export_span(flightrec.span_event(
                name, "podracer", start, end))
        except Exception:  # noqa: BLE001 — observability never blocks
            pass
