"""Podracer RL substrate: Anakin/Sebulba gangs on slice meshes.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(arXiv 2104.06272). Two topologies over this framework's primitives:

  * **Anakin** — everything co-located on ONE mesh/fault domain: the
    actor gangs and the learner share a slice, weights never cross the
    DCN, and the learner's parameter/batch placement rides a
    `parallel/sharding.py` strategy.
  * **Sebulba** — actor gangs DECOUPLED from the learner gang: each
    actor gang pinned to its own slice fault domain (PR 4 gangs,
    reserved via `util.placement_group.slice_placement_group`), the
    learner on a separate slice; trajectory batches cross via the
    compiled-DAG channel plane, weights broadcast via the shm object
    plane (one put, zero-copy gets).

The act->learn data path is a `tick_replay=True` compiled DAG
(`dag/compiled.py`): a slice preemption mid-rollout migrates the
affected gang uncharged (`preempted_restarts`) with exactly-once batch
delivery, and weight versions observed by actors stay monotonic across
the migration.
"""

from ray_tpu.podracer.topology import (GangPlacement, PodracerConfig,
                                       TopologyPlan, TopologyPlanner)
from ray_tpu.podracer.runtime import PodracerRun

__all__ = ["PodracerConfig", "TopologyPlanner", "TopologyPlan",
           "GangPlacement", "PodracerRun"]
