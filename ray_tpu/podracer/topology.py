"""Topology planner: place a learner gang + N actor gangs on fault domains.

The planner turns the cluster's slice inventory (NodeInfo.slice_id — the
PR 4 fault-domain key) into a `TopologyPlan`:

  * **Sebulba** (decoupled): the learner gang takes one slice, each
    actor gang is pinned to a DIFFERENT slice (round-robin over the
    rest) — one preemption can never take both an actor gang and the
    learner. Each gang's slice is gang-reserved with
    `slice_placement_group` (STRICT_SPREAD, one bundle per host) so the
    GCS's atomic gang-drain machinery re-places the whole footprint on
    a replacement domain; the gang's host-side actor processes ride
    soft NodeAffinity onto the same hosts (soft: a drain migrates them
    off instead of wedging them on a dead node).
  * **Anakin** (co-located): every role shares ONE domain (the largest
    slice, or the driver's node off-slice); the learner's param/batch
    placement is a `parallel/sharding.py` strategy over the local mesh.

Sliceless clusters (CI boxes, laptops) degrade gracefully: no
placement groups, actor gangs spread round-robin across alive nodes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.parallel.mesh import SliceInfo


@dataclass
class PodracerConfig:
    """One knob set for planner + runtime (kept flat on purpose: the
    whole config crosses to actor constructors as plain values)."""

    mode: str = "sebulba"              # "sebulba" | "anakin"
    env: Any = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_actor_gangs: int = 2
    actors_per_gang: int = 1
    num_envs: int = 1                  # env copies per actor
    fragment_len: int = 16             # steps per env per tick
    hidden: tuple = (32, 32)
    lr: float = 5e-4
    gamma: float = 0.99
    lam: float = 0.95
    minibatch_size: int = 64
    num_epochs: int = 1
    seed: int = 0
    # Weight broadcast cadence: a new version is put to the object plane
    # every `broadcast_interval` learner updates (1 = every tick).
    broadcast_interval: int = 1
    # Compiled-DAG channel tuning: depth bounds pipelined ticks in
    # flight (= how stale actor weights may run under execute_async).
    channel_depth: int = 2
    max_message_size: int = 1 << 20
    # Anakin learner placement strategy (parallel/sharding preset name).
    learner_sharding: str = "dp"
    # Gang-reserve each gang's slice with a slice_placement_group.
    # None = auto (reserve when the slice exposes TPU resources).
    reserve_slices: Optional[bool] = None
    actor_num_cpus: float = 1.0
    learner_num_cpus: float = 1.0

    def steps_per_tick(self) -> int:
        return (self.num_actor_gangs * self.actors_per_gang
                * self.num_envs * self.fragment_len)


@dataclass
class GangPlacement:
    """Where one gang (learner or actor gang) lives."""

    role: str                          # "learner" | "actors[i]"
    slice_id: str = ""                 # "" = off-slice
    node_ids: List[str] = field(default_factory=list)
    # Per-member .options() kwargs (scheduling_strategy etc.), one per
    # gang member, round-robin over the domain's hosts.
    member_options: List[Dict[str, Any]] = field(default_factory=list)
    placement_group: Any = None        # slice reservation (or None)


@dataclass
class TopologyPlan:
    mode: str
    learner: GangPlacement = None
    actor_gangs: List[GangPlacement] = field(default_factory=list)
    sharding: Any = None               # ShardingStrategy (Anakin learner)
    slices: Dict[str, List[str]] = field(default_factory=dict)

    def describe(self) -> dict:
        return {
            "mode": self.mode,
            "learner_slice": self.learner.slice_id if self.learner else "",
            "actor_slices": [g.slice_id for g in self.actor_gangs],
            "reserved": sum(1 for g in ([self.learner] + self.actor_gangs)
                            if g and g.placement_group is not None),
            "sharding": getattr(self.sharding, "name", None),
        }

    def teardown(self):
        """Release every slice reservation the plan holds."""
        from ray_tpu.util.placement_group import remove_placement_group
        for g in [self.learner] + list(self.actor_gangs):
            if g is not None and g.placement_group is not None:
                try:
                    remove_placement_group(g.placement_group)
                except Exception:  # noqa: BLE001 — cluster already down
                    pass
                g.placement_group = None


def _slice_info_from_nodes(slice_id: str, nodes: List[dict]) -> SliceInfo:
    """Reconstruct a SliceInfo from the GCS's view of one fault domain
    (fake clusters and real TPU VMs both register per-host TPU totals +
    a head resource on host 0)."""
    per_host = max((float(n["Resources"].get("TPU", 0.0)) for n in nodes),
                   default=0.0)
    name = ""
    for n in nodes:
        for res in n["Resources"]:
            if res.startswith("TPU-") and res.endswith("-head"):
                name = res[len("TPU-"):-len("-head")]
                break
        if name:
            break
    return SliceInfo(name=name, num_chips=int(per_host * len(nodes)),
                     num_hosts=len(nodes),
                     chips_per_host=int(per_host) or 4)


class TopologyPlanner:
    """Maps PodracerConfig roles onto the live cluster's fault domains."""

    def __init__(self, config: PodracerConfig):
        if config.mode not in ("sebulba", "anakin"):
            raise ValueError(f"unknown podracer mode {config.mode!r} "
                             f"(one of 'sebulba', 'anakin')")
        self.config = config

    # -- cluster inventory --------------------------------------------
    def _inventory(self):
        from ray_tpu._private import worker_api
        alive = [n for n in worker_api.nodes()
                 if n["Alive"] and not n.get("Draining")]
        slices: Dict[str, List[dict]] = {}
        for n in alive:
            sid = n.get("SliceId") or ""
            if sid:
                slices.setdefault(sid, []).append(n)
        return alive, dict(sorted(slices.items()))

    def _reserve(self, role: str, slice_id: str,
                 members: List[dict]):
        """Gang-reserve one slice for `role` (STRICT_SPREAD, one bundle
        per host) so the PR 4 machinery migrates the footprint as a
        unit. Skipped when the slice exposes no TPU resources (nothing
        to reserve) unless explicitly forced."""
        reserve = self.config.reserve_slices
        has_tpu = any(float(n["Resources"].get("TPU", 0.0)) > 0
                      for n in members)
        if reserve is None:
            reserve = has_tpu
        if not reserve or not has_tpu:
            return None
        from ray_tpu.util.placement_group import slice_placement_group
        info = _slice_info_from_nodes(slice_id, members)
        pg = slice_placement_group(info, name=f"podracer-{role}")
        pg.wait(timeout_seconds=30.0)
        return pg

    @staticmethod
    def _member_options(nodes: List[dict], count: int) -> List[dict]:
        """Soft NodeAffinity round-robin over the domain's hosts: the
        scheduler lands members on the gang's slice, and a drain can
        still migrate them off (hard affinity would pin a migrating
        actor to its dead node forever)."""
        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        if not nodes:
            return [{} for _ in range(count)]
        out = []
        for i in range(count):
            node = nodes[i % len(nodes)]
            out.append({"scheduling_strategy": NodeAffinitySchedulingStrategy(
                node["NodeID"], soft=True)})
        return out

    def _gang(self, role: str, slice_id: str, members: List[dict],
              count: int, reserve: bool = True) -> GangPlacement:
        pg = self._reserve(role, slice_id, members) \
            if (slice_id and reserve) else None
        return GangPlacement(
            role=role, slice_id=slice_id,
            node_ids=[n["NodeID"] for n in members],
            member_options=self._member_options(members, count),
            placement_group=pg)

    # -- planning ------------------------------------------------------
    def plan(self) -> TopologyPlan:
        cfg = self.config
        alive, slices = self._inventory()
        plan = TopologyPlan(mode=cfg.mode,
                            slices={s: [n["NodeID"] for n in ns]
                                    for s, ns in slices.items()})
        slice_ids = list(slices)
        if cfg.mode == "anakin":
            self._plan_anakin(plan, alive, slices, slice_ids)
        else:
            self._plan_sebulba(plan, alive, slices, slice_ids)
        self._export_span(plan)
        return plan

    def _plan_anakin(self, plan: TopologyPlan, alive, slices, slice_ids):
        """Co-located: one domain hosts learner AND every actor gang;
        the learner's device placement is a sharding strategy over that
        mesh (act/learn share the chips, the Anakin premise)."""
        cfg = self.config
        from ray_tpu.parallel.sharding import strategy_from_name
        plan.sharding = strategy_from_name(cfg.learner_sharding)
        if slice_ids:
            # Largest slice wins (most chips to co-locate onto).
            home = max(slice_ids, key=lambda s: len(slices[s]))
            members = slices[home]
        else:
            home, members = "", self._driver_home(alive)
        plan.learner = self._gang("learner", home, members, 1)
        for g in range(cfg.num_actor_gangs):
            # The learner's reservation covers the shared domain —
            # actor gangs must not double-reserve the same slice.
            plan.actor_gangs.append(self._gang(
                f"actors{g}", home, members, cfg.actors_per_gang,
                reserve=False))

    def _plan_sebulba(self, plan: TopologyPlan, alive, slices, slice_ids):
        """Decoupled: learner slice first, actor gangs round-robin over
        the REMAINING slices; with a single slice the actors take it
        and the learner runs off-slice; with none, round-robin nodes."""
        cfg = self.config
        if len(slice_ids) >= 2:
            learner_members = slices[slice_ids[0]]
            plan.learner = self._gang("learner", slice_ids[0],
                                      learner_members, 1)
            actor_sids = slice_ids[1:]
            reserved = set()
            for g in range(cfg.num_actor_gangs):
                sid = actor_sids[g % len(actor_sids)]
                plan.actor_gangs.append(self._gang(
                    f"actors{g}", sid, slices[sid], cfg.actors_per_gang,
                    reserve=sid not in reserved))
                reserved.add(sid)
        elif len(slice_ids) == 1:
            sid = slice_ids[0]
            off_slice = [n for n in alive if not n.get("SliceId")]
            plan.learner = self._gang(
                "learner", "", off_slice or self._driver_home(alive), 1)
            reserved = False
            for g in range(cfg.num_actor_gangs):
                plan.actor_gangs.append(self._gang(
                    f"actors{g}", sid, slices[sid], cfg.actors_per_gang,
                    reserve=not reserved))
                reserved = True
        else:
            home = self._driver_home(alive)
            others = [n for n in alive if n not in home] or home
            plan.learner = self._gang("learner", "", home, 1)
            for g in range(cfg.num_actor_gangs):
                members = [others[g % len(others)]]
                plan.actor_gangs.append(self._gang(
                    f"actors{g}", "", members, cfg.actors_per_gang))

    @staticmethod
    def _driver_home(alive: List[dict]) -> List[dict]:
        head = [n for n in alive if n.get("IsHead")]
        return head or alive[:1]

    @staticmethod
    def _export_span(plan: TopologyPlan):
        try:
            import time

            from ray_tpu._private import flightrec
            from ray_tpu.util import tracing
            now = time.time()
            tracing.export_span(flightrec.span_event(
                "podracer:plan", f"podracer:{plan.mode}", now, now))
        except Exception:  # noqa: BLE001 — observability never blocks
            pass
