"""User-facing exceptions (capability parity with python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Re-raised at ``get()`` on the caller with the remote traceback attached.
    """

    def __init__(self, cause: BaseException | None, traceback_str: str = "",
                 task_id=None, pid: int | None = None, node: str | None = None):
        self.cause = cause
        self.traceback_str = traceback_str
        self.task_id = task_id
        self.pid = pid
        self.node = node
        super().__init__(str(cause))

    def __str__(self):
        where = f" (pid={self.pid}, node={self.node})" if self.pid else ""
        return (
            f"Task failed{where}: {type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ---\n{self.traceback_str}"
        )

    def __reduce__(self):
        import pickle
        try:
            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = RayTpuError(f"{type(self.cause).__name__}: {self.cause}")
        return (TaskError, (cause, self.traceback_str, self.task_id,
                            self.pid, self.node))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} is dead: {reason}")


class ActorUnavailableError(ActorError):
    """Actor is temporarily unreachable (e.g., restarting)."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_ref=None, reason: str = "object lost"):
        self.object_ref = object_ref
        super().__init__(f"Object {object_ref} lost: {reason}")


class ObjectFreedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    """The owner (submitting worker) of this object died; value unrecoverable."""

    def __init__(self, object_ref=None):
        ObjectLostError.__init__(self, object_ref, "owner died")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when a task is killed by the node memory monitor."""


class RayTpuSystemError(RayTpuError):
    """Internal invariant violation or control-plane failure."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class CrossLanguageError(RayTpuError):
    pass
