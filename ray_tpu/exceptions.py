"""User-facing exceptions (capability parity with python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Re-raised at ``get()`` on the caller with the remote traceback attached.
    """

    def __init__(self, cause: BaseException | None, traceback_str: str = "",
                 task_id=None, pid: int | None = None, node: str | None = None):
        self.cause = cause
        self.traceback_str = traceback_str
        self.task_id = task_id
        self.pid = pid
        self.node = node
        super().__init__(str(cause))

    def __str__(self):
        where = f" (pid={self.pid}, node={self.node})" if self.pid else ""
        return (
            f"Task failed{where}: {type(self.cause).__name__}: {self.cause}\n"
            f"--- remote traceback ---\n{self.traceback_str}"
        )

    def __reduce__(self):
        import pickle
        try:
            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = RayTpuError(f"{type(self.cause).__name__}: {self.cause}")
        return (TaskError, (cause, self.traceback_str, self.task_id,
                            self.pid, self.node))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly.

    ``preempted`` marks deaths caused by a planned node drain (autoscaler
    downscale / spot reclaim): such failures are retried without charging
    the task's ``max_retries`` budget.
    """

    def __init__(self, *args, preempted: bool = False):
        self.preempted = preempted
        super().__init__(*args)

    def __reduce__(self):
        # Keep the preempted flag across pickling (task errors ship
        # serialized inside return objects; the default reduction replays
        # only self.args).
        return (_rebuild_worker_crashed, (self.args, self.preempted))


def _rebuild_worker_crashed(args, preempted):
    return WorkerCrashedError(*args, preempted=preempted)


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    def __init__(self, actor_id=None, reason: str = "actor died",
                 preempted: bool = False):
        self.actor_id = actor_id
        self.reason = reason
        self.preempted = preempted
        super().__init__(f"Actor {actor_id} is dead: {reason}")

    def __reduce__(self):
        # Rebuild from the real fields: the default reduction would replay
        # the formatted message into actor_id and drop preempted.
        return (ActorDiedError, (self.actor_id, self.reason, self.preempted))


class ActorUnavailableError(ActorError):
    """Actor is temporarily unreachable (e.g., restarting)."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_ref=None, reason: str = "object lost"):
        self.object_ref = object_ref
        super().__init__(f"Object {object_ref} lost: {reason}")


class ObjectFreedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    """The owner (submitting worker) of this object died; value unrecoverable."""

    def __init__(self, object_ref=None):
        ObjectLostError.__init__(self, object_ref, "owner died")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class NodeDiedError(RayTpuError):
    pass


class NodeDrainedError(RayTpuError):
    """Work was lost to a *planned* node removal (two-phase drain).

    Raised only when the graceful path cannot absorb the loss (e.g. tasks
    queued on a draining node with no feasible peer); drain-caused retries
    themselves never charge the user's retry budgets.
    """

    def __init__(self, node_id=None, reason: str = "node drained"):
        self.node_id = node_id
        self.reason = reason
        super().__init__(f"Node {node_id} drained: {reason}")

    def __reduce__(self):
        return (NodeDrainedError, (self.node_id, self.reason))


class DagExecutionError(RayTpuError):
    """A compiled DAG can no longer execute: an executor loop / pinned
    worker died mid-tick, or the pipeline was torn down underneath an
    in-flight execute. Raised on the in-flight execute AND every
    subsequent one — the DAG must be torn down and recompiled.

    Application errors raised by a bound method are NOT wrapped in this;
    they re-raise as themselves and the pipeline keeps ticking.
    """

    def __init__(self, reason: str = "compiled DAG executor died",
                 cause: BaseException | None = None):
        self.reason = reason
        self.cause = cause
        detail = f": {type(cause).__name__}: {cause}" if cause else ""
        super().__init__(f"{reason}{detail}")

    def __reduce__(self):
        import pickle
        cause = self.cause
        try:
            pickle.dumps(cause)
        except Exception:
            cause = RayTpuError(f"{type(self.cause).__name__}: {self.cause}")
        # type(self), not the base class: DagRecoveryError must survive
        # a pickle round trip as itself.
        return (type(self), (self.reason, cause))


class DagRecoveryError(DagExecutionError):
    """In-place recovery of a `tick_replay` compiled DAG failed: a
    participant died for good (max_restarts exhausted), re-pinning its
    replacement's lease failed repeatedly, or the recovery timed out.
    Subclasses DagExecutionError so existing fail-fast handlers keep
    working; the DAG must be torn down and recompiled.
    """

    def __init__(self, reason: str = "compiled DAG recovery failed",
                 cause: BaseException | None = None):
        super().__init__(reason, cause)


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when a task is killed by the node memory monitor."""


class RayTpuSystemError(RayTpuError):
    """Internal invariant violation or control-plane failure."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class CrossLanguageError(RayTpuError):
    pass
