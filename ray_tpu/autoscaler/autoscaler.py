"""Demand-driven autoscaler.

Reference: python/ray/autoscaler/_private/autoscaler.py:171
(StandardAutoscaler) + resource_demand_scheduler.py:102 (demand
bin-packing). TPU-first deltas: node types can declare `slice_hosts` so a
TPU pod slice scales as one gang unit, and STRICT_SPREAD placement groups
count one node per bundle (the slice/gang unit of the scheduler).

The autoscaler is deliberately a pure control loop over GCS state:
  demand  = queued worker-lease shapes (raylet heartbeats)
          + bundles of unplaced placement groups
  supply  = alive nodes' available resources + capacity of launching nodes
  unmet demand -> bin-pack onto node types -> provider.create_node
  idle nodes (available == total for > idle_timeout) -> terminate.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    # TPU: hosts per slice; create_node launches the whole gang.
    slice_hosts: int = 1

    def fits(self, shape: Dict[str, float]) -> bool:
        return all(self.resources.get(k, 0.0) >= v
                   for k, v in shape.items() if v > 0)


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    max_launch_batch: int = 8
    update_interval_s: float = 5.0
    # Deadline handed to the GCS DrainNode when removing an idle node:
    # idle nodes report drain-complete almost immediately, the deadline
    # only bounds the wait.
    drain_deadline_s: float = 15.0
    # Deadline when reacting to a provider preemption notice (spot/
    # preemptible reclaim): the cloud gives ~30s of warning, so object
    # migration + actor moves must fit inside it.
    preempt_deadline_s: float = 10.0

    @staticmethod
    def from_dict(d: dict) -> "AutoscalerConfig":
        types = {
            name: NodeTypeConfig(
                name=name, resources=dict(t.get("resources", {"CPU": 1})),
                min_workers=int(t.get("min_workers", 0)),
                max_workers=int(t.get("max_workers", 10)),
                slice_hosts=int(t.get("slice_hosts", 1)))
            for name, t in d.get("node_types", {}).items()}
        return AutoscalerConfig(
            node_types=types,
            idle_timeout_s=float(d.get("idle_timeout_s", 60.0)),
            max_launch_batch=int(d.get("max_launch_batch", 8)),
            update_interval_s=float(d.get("update_interval_s", 5.0)),
            drain_deadline_s=float(d.get("drain_deadline_s", 15.0)),
            preempt_deadline_s=float(d.get("preempt_deadline_s", 10.0)))


def node_is_idle(info: dict) -> bool:
    """A GCS node is idle when every schedulable resource is fully
    available (memory/object_store fluctuate with caches and are
    excluded). POLICY shared by both autoscaler engines — change here,
    not in a copy."""
    if not info.get("alive"):
        return False
    return all(abs(info.get("available", {}).get(k, 0.0) - v) < 1e-6
               for k, v in info.get("total", {}).items()
               if k not in ("memory", "object_store_memory"))


def demand_shapes(state: dict) -> List[Dict[str, float]]:
    """Pending demand = queued lease shapes + unplaced PG bundles;
    STRICT_SPREAD bundles are tagged __exclusive__ (one node each).
    Shared by both engines."""
    shapes = [dict(s) for s in state.get("pending_demand", [])]
    for pg in state.get("pending_placement_groups", []):
        for b in pg["bundles"]:
            s = dict(b)
            if pg["strategy"] == "STRICT_SPREAD":
                s["__exclusive__"] = 1.0
            shapes.append(s)
    return shapes


class StandardAutoscaler:
    """One update() = one reconcile pass. Drive it from Monitor (live) or
    directly from tests (deterministic)."""

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 gcs_request):
        """gcs_request: callable(method: str, payload: dict) -> result
        (synchronous; the Monitor wraps the async GCS client)."""
        self.config = config
        self.provider = provider
        self.gcs_request = gcs_request
        self._idle_since: Dict[tuple, float] = {}   # gang/unit key -> ts
        self._last_state: Optional[dict] = None
        # Slice gangs: provider node id -> tuple of all ids launched in the
        # same create_node gang (slice_hosts > 1 scales whole slices).
        self._gang_of: Dict[str, tuple] = {}
        # Provider nodes whose preemption notice already triggered a drain;
        # terminated (reaped) once the GCS no longer reports them alive.
        self._preempt_draining: Dict[str, float] = {}   # pid -> drain ts
        # Noticed gang members with NO GCS registration: first sighting is
        # recorded, provider-side terminate happens only on a LATER pass
        # still unregistered — a member whose registration raced this
        # pass's state snapshot keeps its graceful drain.
        self._unregistered_notice: Dict[str, float] = {}  # pid -> first ts

    # ---------------- slice (gang) accounting ----------------

    def _slices_of_type(self, type_name: str,
                        t: "NodeTypeConfig") -> int:
        """Number of gang units of a type: tracked gangs count once, nodes
        launched outside this autoscaler count host/slice_hosts rounded up."""
        gangs = set()
        loose = 0
        for pid in self.provider.non_terminated_nodes():
            if self.provider.node_tags(pid).get("node_type") != type_name:
                continue
            gang = self._gang_of.get(pid)
            if gang is not None:
                gangs.add(gang)
            else:
                loose += 1
        per = max(1, t.slice_hosts)
        return len(gangs) + -(-loose // per)

    def _launch_slice(self, t: "NodeTypeConfig") -> int:
        pids = self.provider.create_node(
            t.name, {"resources": dict(t.resources)}, max(1, t.slice_hosts))
        gang = tuple(pids)
        for pid in pids:
            self._gang_of[pid] = gang
        return len(pids)

    # ---------------- demand/supply computation ----------------

    def _demand_shapes(self, state: dict) -> List[Dict[str, float]]:
        return demand_shapes(state)

    def _correlate(self, state: dict):
        """Provider-node ↔ GCS-node correlation, shared by every consumer
        of one reconcile pass. Returns (alive_by_hex, gcs_hex_of):
        alive_by_hex maps every known GCS node hex to its alive flag;
        gcs_hex_of(pid, tags=None) resolves a provider node id through
        either channel — the provider's own node_id tag (local providers)
        or the ray_tpu.io/provider-id label cloud nodes register with
        (the cloud API never sees GCS ids)."""
        alive_by_hex: Dict[str, bool] = {}
        hex_by_provider: Dict[str, str] = {}
        for nid, info in state.get("nodes", {}).items():
            h = nid.hex() if hasattr(nid, "hex") else str(nid)
            alive_by_hex[h] = bool(info.get("alive"))
            p = (info.get("labels") or {}).get("ray_tpu.io/provider-id")
            if p:
                hex_by_provider[p] = h

        def gcs_hex_of(pid: str, tags: Optional[Dict[str, str]] = None) -> str:
            if tags is None:
                tags = self.provider.node_tags(pid)
            nid = tags.get("node_id", "")
            if nid in alive_by_hex:
                return nid
            return hex_by_provider.get(pid, "")

        return alive_by_hex, gcs_hex_of

    def update(self) -> dict:
        """One reconcile pass; returns {launched: {type: n}, terminated: [...]}.
        """
        state = self.gcs_request("get_autoscaler_state", {})
        self._last_state = state
        launched: Dict[str, int] = {}
        terminated: List[str] = []
        terminated.extend(self._handle_preemption_notices(state))

        # ---- supply view: available capacity per alive node ----
        # Each entry: {"cap": resources, "exclusive_taken": bool}.
        _known, gcs_hex_of = self._correlate(state)
        # Draining nodes are NOT supply: the GCS refuses them new work, so
        # counting their free capacity would suppress the replacement
        # launch for exactly the demand their drain displaces.
        bins: List[dict] = [
            {"cap": dict(n["available"]), "exclusive_taken": False}
            for n in state["nodes"].values()
            if n["alive"] and not n.get("draining")]
        # Nodes the provider launched that haven't registered with the GCS
        # yet (startup race): count their full declared shape so a second
        # update() pass doesn't double-launch.
        for pid in self.provider.non_terminated_nodes():
            tags = self.provider.node_tags(pid)
            if not gcs_hex_of(pid, tags):
                t = self.config.node_types.get(tags.get("node_type", ""))
                if t:
                    bins.append({"cap": dict(t.resources),
                                 "exclusive_taken": False})

        def try_place(shape: Dict[str, float], exclusive: bool) -> bool:
            for b in bins:
                if exclusive and b["exclusive_taken"]:
                    continue
                if all(b["cap"].get(k, 0.0) >= v
                       for k, v in shape.items() if v > 0):
                    for k, v in shape.items():
                        if v > 0:
                            b["cap"][k] = b["cap"].get(k, 0.0) - v
                    if exclusive:
                        b["exclusive_taken"] = True
                    return True
            return False

        # ---- bin-pack demand; launch the smallest type that fits ----
        # All caps/counts below are in SLICES (gang units): one slice =
        # slice_hosts provider nodes, launched and terminated together.
        to_launch: Dict[str, int] = {}
        for shape in self._demand_shapes(state):
            exclusive = shape.pop("__exclusive__", 0.0) > 0
            if try_place(shape, exclusive):
                continue
            for t in sorted(self.config.node_types.values(),
                            key=lambda t: sum(t.resources.values())):
                current = self._slices_of_type(t.name, t)
                if t.fits(shape) and current + to_launch.get(t.name, 0) \
                        < t.max_workers:
                    to_launch[t.name] = to_launch.get(t.name, 0) + 1
                    cap = dict(t.resources)
                    for k, v in shape.items():
                        if v > 0:
                            cap[k] = cap.get(k, 0.0) - v
                    bins.append({"cap": cap, "exclusive_taken": exclusive})
                    break
            else:
                logger.warning("autoscaler: demand %s fits no node type",
                               shape)

        # ---- honor min_workers (in slices) ----
        for t in self.config.node_types.values():
            current = self._slices_of_type(t.name, t)
            short = t.min_workers - current - to_launch.get(t.name, 0)
            if short > 0:
                to_launch[t.name] = to_launch.get(t.name, 0) + short

        # ---- launch ----
        for type_name, count in to_launch.items():
            t = self.config.node_types[type_name]
            count = min(count, self.config.max_launch_batch)
            n_created = sum(self._launch_slice(t) for _ in range(count))
            launched[type_name] = n_created
            logger.info("autoscaler: launched %d hosts (%d slices) of %s",
                        n_created, count, type_name)

        # ---- scale down idle slices (whole gangs only) ----
        now = time.time()
        demand_left = bool(self._demand_shapes(state))
        gcs_by_hex = {
            (gid.hex() if hasattr(gid, "hex") else str(gid)): info
            for gid, info in state["nodes"].items()}

        def node_idle(pid: str) -> bool:
            n = gcs_by_hex.get(gcs_hex_of(pid, self.provider.node_tags(pid)))
            return n is not None and node_is_idle(n)

        units: Dict[tuple, List[str]] = {}
        for pid in self.provider.non_terminated_nodes():
            key = self._gang_of.get(pid, (pid,))
            units.setdefault(key, []).append(pid)
        for key, pids in units.items():
            tags = self.provider.node_tags(pids[0])
            t = self.config.node_types.get(tags.get("node_type", ""))
            if not all(node_idle(p) for p in pids) or demand_left:
                self._idle_since.pop(key, None)
                continue
            first = self._idle_since.setdefault(key, now)
            if (now - first >= self.config.idle_timeout_s and t is not None
                    and self._slices_of_type(t.name, t) > t.min_workers):
                logger.info("autoscaler: terminating idle slice %s", pids)
                # Two-phase removal: drain with a deadline and wait for
                # the GCS to mark the nodes dead (idle nodes report
                # drain-complete immediately) BEFORE reclaiming the VMs —
                # terminating first would turn a planned removal into a
                # crash for any straggler work. Drains for the whole slice
                # are issued fire-and-forget and only the LAST carries the
                # (bounded, well under make_gcs_request's 30s bridge)
                # wait, so a 16-host gang pays one wait, not 16; one state
                # fetch then confirms which hosts actually died. Hosts
                # still alive defer to the preemption-reap path instead of
                # being killed busy (or leaking on a bridge TimeoutError).
                nid_of = {pid: gcs_hex_of(pid, self.provider.node_tags(pid))
                          for pid in pids}
                for i, pid in enumerate(pids):
                    last = i == len(pids) - 1
                    self.gcs_request("drain_node", {
                        "node_id_hex": nid_of[pid],
                        "deadline_s": self.config.drain_deadline_s,
                        "grace_s": 0.0, "wait": last, "wait_timeout_s": 15.0,
                        "reason": "autoscaler downscale (idle)"})
                post = self.gcs_request("get_autoscaler_state", {})
                alive_hexes = {
                    (k.hex() if hasattr(k, "hex") else str(k))
                    for k, n in post.get("nodes", {}).items()
                    if n.get("alive")}
                for pid in pids:
                    if nid_of[pid] in alive_hexes:
                        self._preempt_draining[pid] = time.time()
                        continue
                    self.provider.terminate_node(pid)
                    self._gang_of.pop(pid, None)
                    terminated.append(pid)
                self._idle_since.pop(key, None)
        return {"launched": launched, "terminated": terminated}

    # ---------------- preemption notices ----------------

    def _handle_preemption_notices(self, state: dict) -> List[str]:
        """Poll the provider's preemption-notice source (GCE spot reclaim
        warnings, test hooks) and convert each notice into a drain with a
        tight deadline; reap the provider node once the GCS reports it
        gone. Returns the provider ids reaped this pass."""
        reaped: List[str] = []
        try:
            notices = self.provider.preemption_notices()
        except Exception:  # noqa: BLE001 — a flaky notice poll must not
            logger.exception("preemption notice poll failed")  # stop scaling
            notices = []
        alive_by_hex, gcs_hex_of = self._correlate(state)

        for pid in notices:
            # Slice gangs fail as one unit: a notice for any member means
            # the whole slice is going away — drain and reap every host
            # of the gang, not just the noticed one. (The GCS escalates
            # the drain to the slice fault domain on its side too; this
            # keeps the PROVIDER view consistent so sibling VMs are
            # terminated instead of lingering as zombie capacity.)
            # Skip only once EVERY member is marked: gating on the
            # noticed pid alone would strand a sibling that had no GCS
            # registration (or hit a GCS hiccup) on the first pass.
            gang = self._gang_of.get(pid, (pid,))
            if all(m in self._preempt_draining for m in gang):
                continue
            first = True
            for member in gang:
                if member in self._preempt_draining:
                    continue
                nid = gcs_hex_of(member)
                if not nid:
                    # No GCS registration for a noticed gang member. One
                    # retry pass first — a registration racing this
                    # pass's state snapshot deserves its graceful drain;
                    # a member STILL unregistered next pass never came
                    # up (died during boot / preemption beat it): there
                    # is nothing to drain, so reclaim the instance
                    # provider-side — the old skip-forever path leaked
                    # it (gcs_hex_of stays empty, so the drain path
                    # never marks it and the reaper below never fires).
                    if member not in self._unregistered_notice:
                        self._unregistered_notice[member] = time.time()
                        continue  # retry once: may register next pass
                    try:
                        self.provider.terminate_node(member)
                    except Exception:  # noqa: BLE001 — cloud reclaimed it
                        pass
                    logger.warning(
                        "autoscaler: preemption notice for %s, which "
                        "never registered; terminated provider-side",
                        member)
                    self._unregistered_notice.pop(member, None)
                    # Marked so the gang gate + reap loop see it handled;
                    # the reap pass pops it once the provider confirms.
                    self._preempt_draining[member] = time.time()
                    continue
                self._unregistered_notice.pop(member, None)
                logger.warning(
                    "autoscaler: preemption notice for %s (gcs node %s%s); "
                    "draining", member, nid[:12],
                    "" if first else f", gang of {pid}")
                first = False
                self.gcs_request("drain_node", {
                    "node_id_hex": nid,
                    "deadline_s": self.config.preempt_deadline_s,
                    "reason": "preemption notice"})
                # Recorded only after the request went through: a GCS
                # hiccup here leaves the member unmarked so the next
                # pass retries the drain (rpc_drain_node is idempotent).
                self._preempt_draining[member] = time.time()
        for pid in list(self._preempt_draining):
            gone_from_provider = pid not in self.provider.non_terminated_nodes()
            nid = gcs_hex_of(pid)
            if gone_from_provider or (nid and not alive_by_hex.get(nid, True)):
                if not gone_from_provider:
                    try:
                        self.provider.terminate_node(pid)
                    except Exception:  # noqa: BLE001 — cloud reclaimed it
                        pass
                self._gang_of.pop(pid, None)
                self._preempt_draining.pop(pid, None)
                reaped.append(pid)
        return reaped
