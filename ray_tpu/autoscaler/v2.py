"""Autoscaler v2: instance-manager architecture.

Reference parity: python/ray/autoscaler/v2/ — the v2 redesign splits the
monolithic StandardAutoscaler loop into:

  - InstanceManager (instance_manager/instance_manager.py): the ONLY
    writer of a versioned instance table; every instance walks an explicit
    lifecycle state machine and every transition is validated + recorded.
  - Reconciler (instance_manager/reconciler.py): diffs the table against
    the two external views — the cloud provider's instance list and the
    GCS node table — and applies the resulting transitions.
  - Scheduler (scheduler.py): pure demand -> target-shape computation.

The v1 loop (autoscaler.py StandardAutoscaler) stays as the default; v2
runs against the SAME NodeProvider implementations (fake / GCE TPU / k8s)
and the same GCS autoscaler-state RPC, so either engine can drive a
cluster. TPU slice gangs scale as one instance whose `count` is the
slice's host count (the gang unit is an instance, not a host).

Instance lifecycle (reference: instance_manager/common.py InstanceUtil
transition graph):

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPING
                 |             |             |              |
                 v             v             v              v
          ALLOCATION_FAILED  TERMINATED <- TERMINATING <----+
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig,
                                           NodeTypeConfig, demand_shapes,
                                           node_is_idle)
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# instance states
# ---------------------------------------------------------------------------

QUEUED = "QUEUED"                        # wanted; no cloud call yet
REQUESTED = "REQUESTED"                  # create_node issued
ALLOCATED = "ALLOCATED"                  # provider lists the node(s)
RAY_RUNNING = "RAY_RUNNING"              # registered with the GCS
RAY_STOPPING = "RAY_STOPPING"            # drain requested
TERMINATING = "TERMINATING"             # terminate_node issued
TERMINATED = "TERMINATED"               # gone from the provider
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # create_node raised

_TRANSITIONS: Dict[str, Tuple[str, ...]] = {
    QUEUED: (REQUESTED,),
    REQUESTED: (ALLOCATED, ALLOCATION_FAILED),
    ALLOCATED: (RAY_RUNNING, TERMINATING, TERMINATED),
    RAY_RUNNING: (RAY_STOPPING, TERMINATING, TERMINATED),
    RAY_STOPPING: (TERMINATING, TERMINATED),
    TERMINATING: (TERMINATED,),
    TERMINATED: (),
    ALLOCATION_FAILED: (QUEUED,),        # retry path
}


class InvalidTransitionError(ValueError):
    pass


class VersionConflictError(RuntimeError):
    pass


@dataclass
class Instance:
    instance_id: str
    node_type: str
    state: str = QUEUED
    # Provider node ids backing this instance (slice gangs: all hosts).
    provider_ids: Tuple[str, ...] = ()
    gcs_node_ids: Tuple[str, ...] = ()
    version: int = 0
    launch_attempts: int = 0
    # [(state, unix_ts)] — the reference keeps the same audit trail.
    history: List[Tuple[str, float]] = field(default_factory=list)

    def seen(self, state: str) -> bool:
        return any(s == state for s, _ in self.history)


class InstanceManager:
    """Versioned instance table; the only mutation path is
    update_instance, which validates the lifecycle transition and bumps
    the version (optimistic concurrency, reference
    instance_manager.py:update_instance_manager_state)."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}
        self._next = itertools.count()

    def add_instance(self, node_type: str) -> Instance:
        iid = f"inst-{next(self._next)}"
        inst = Instance(instance_id=iid, node_type=node_type,
                        history=[(QUEUED, time.time())])
        self._instances[iid] = inst
        return inst

    def get(self, instance_id: str) -> Instance:
        return self._instances[instance_id]

    def instances(self, states: Optional[Tuple[str, ...]] = None
                  ) -> List[Instance]:
        out = list(self._instances.values())
        if states is not None:
            out = [i for i in out if i.state in states]
        return out

    def prune(self, keep_dead: int = 50) -> int:
        """Drop all but the most recent `keep_dead` dead instances
        (TERMINATED, and ALLOCATION_FAILED ones no longer being retried)
        so a long-running autoscaler's table stays bounded; the audit
        trail of recent churn is retained for debugging. Returns the
        number removed."""
        dead = [i for i in self._instances.values()
                if i.state in (TERMINATED, ALLOCATION_FAILED)]
        dead.sort(key=lambda i: i.history[-1][1])
        removed = 0
        for inst in dead[:max(0, len(dead) - keep_dead)]:
            del self._instances[inst.instance_id]
            removed += 1
        return removed

    def update_instance(self, instance_id: str, new_state: str, *,
                        expected_version: Optional[int] = None,
                        provider_ids: Optional[Tuple[str, ...]] = None,
                        gcs_node_ids: Optional[Tuple[str, ...]] = None
                        ) -> Instance:
        inst = self._instances[instance_id]
        if expected_version is not None and \
                inst.version != expected_version:
            raise VersionConflictError(
                f"{instance_id}: version {inst.version} != "
                f"expected {expected_version}")
        if new_state not in _TRANSITIONS[inst.state]:
            raise InvalidTransitionError(
                f"{instance_id}: {inst.state} -> {new_state} not allowed")
        inst.state = new_state
        inst.version += 1
        inst.history.append((new_state, time.time()))
        if provider_ids is not None:
            inst.provider_ids = tuple(provider_ids)
        if gcs_node_ids is not None:
            inst.gcs_node_ids = tuple(gcs_node_ids)
        return inst


# ---------------------------------------------------------------------------
# scheduler: demand -> per-type launch/terminate decisions (pure)
# ---------------------------------------------------------------------------

def compute_scaling_decision(
        demand_shapes: List[Dict[str, float]],
        node_types: Dict[str, NodeTypeConfig],
        available_bins: List[Dict[str, float]],
        active_counts: Dict[str, int]) -> Dict[str, int]:
    """Bin-pack unmet demand onto the cheapest fitting node type.

    Pure function (reference: v2/scheduler.py ResourceDemandScheduler):
    no provider or table access, fully unit-testable. Returns
    {node_type: instances_to_launch}. available_bins are mutated copies
    of per-node available resources; active_counts are CURRENT instance
    counts per type (for max_workers enforcement).
    """
    bins = [{"cap": dict(b), "exclusive_taken": False}
            for b in available_bins]
    to_launch: Dict[str, int] = {}

    def try_place(shape: Dict[str, float], exclusive: bool) -> bool:
        for b in bins:
            if exclusive and b["exclusive_taken"]:
                continue
            if all(b["cap"].get(k, 0.0) >= v
                   for k, v in shape.items() if v > 0):
                for k, v in shape.items():
                    if v > 0:
                        b["cap"][k] = b["cap"].get(k, 0.0) - v
                if exclusive:
                    b["exclusive_taken"] = True
                return True
        return False

    for shape in demand_shapes:
        shape = dict(shape)
        exclusive = shape.pop("__exclusive__", 0.0) > 0
        if try_place(shape, exclusive):
            continue
        for t in sorted(node_types.values(),
                        key=lambda t: sum(t.resources.values())):
            current = (active_counts.get(t.name, 0)
                       + to_launch.get(t.name, 0))
            if t.fits(shape) and current < t.max_workers:
                to_launch[t.name] = to_launch.get(t.name, 0) + 1
                cap = dict(t.resources)
                for k, v in shape.items():
                    if v > 0:
                        cap[k] = cap.get(k, 0.0) - v
                bins.append({"cap": cap, "exclusive_taken": exclusive})
                break
        else:
            logger.warning("v2 scheduler: demand %s fits no node type",
                           shape)
    # min_workers floor
    for t in node_types.values():
        short = (t.min_workers - active_counts.get(t.name, 0)
                 - to_launch.get(t.name, 0))
        if short > 0:
            to_launch[t.name] = to_launch.get(t.name, 0) + short
    return to_launch


# ---------------------------------------------------------------------------
# reconciler
# ---------------------------------------------------------------------------

class Reconciler:
    """Applies the table <-> world diff (reference: v2 reconciler.py):

      QUEUED            -> issue create_node         -> REQUESTED/ALLOCATED
      REQUESTED/ALLOCATED + GCS sees the node        -> RAY_RUNNING
      any active + provider no longer lists its ids  -> TERMINATED
      RAY_STOPPING      -> drain done                -> TERMINATING
      TERMINATING       -> issue terminate_node      -> TERMINATED
      ALLOCATION_FAILED -> requeue (bounded retries) -> QUEUED
    """

    def __init__(self, provider: NodeProvider,
                 node_types: Dict[str, NodeTypeConfig],
                 max_launch_retries: int = 3):
        self.provider = provider
        self.node_types = node_types
        self.max_launch_retries = max_launch_retries

    def reconcile(self, im: InstanceManager, gcs_state: dict,
                  gcs_request=None) -> Dict[str, Any]:
        events: List[str] = []
        # GCS hex ids by the provider-id label (cloud nodes register with
        # a ray_tpu.io/provider-id label; same correlation as v1).
        gcs_by_provider: Dict[str, str] = {}
        gcs_alive: Dict[str, bool] = {}
        gcs_idle: Dict[str, bool] = {}
        for nid, info in gcs_state.get("nodes", {}).items():
            hexid = nid.hex() if hasattr(nid, "hex") else str(nid)
            gcs_alive[hexid] = bool(info.get("alive"))
            gcs_idle[hexid] = node_is_idle(info)
            p = (info.get("labels") or {}).get("ray_tpu.io/provider-id")
            if p:
                gcs_by_provider[p] = hexid

        # 1) launch QUEUED instances.
        for inst in im.instances((QUEUED,)):
            t = self.node_types[inst.node_type]
            im.update_instance(inst.instance_id, REQUESTED)
            inst.launch_attempts += 1
            try:
                pids = self.provider.create_node(
                    t.name, {"resources": dict(t.resources)},
                    max(1, t.slice_hosts))
                im.update_instance(inst.instance_id, ALLOCATED,
                                   provider_ids=tuple(pids))
                events.append(f"{inst.instance_id}: allocated {pids}")
            except Exception as e:  # noqa: BLE001 — cloud call failed
                im.update_instance(inst.instance_id, ALLOCATION_FAILED)
                events.append(f"{inst.instance_id}: allocation failed {e}")

        # 2) requeue bounded allocation failures.
        for inst in im.instances((ALLOCATION_FAILED,)):
            if inst.launch_attempts < self.max_launch_retries:
                im.update_instance(inst.instance_id, QUEUED)
                events.append(f"{inst.instance_id}: requeued "
                              f"(attempt {inst.launch_attempts})")

        # Refresh the provider view: step 1 just created nodes, and the
        # vanished-node check below must not see them as missing.
        alive_provider = set(self.provider.non_terminated_nodes())

        def gcs_hex_of(pid: str) -> str:
            # Two correlation channels (same as v1): local providers tag
            # nodes with the GCS id directly; cloud nodes register a
            # ray_tpu.io/provider-id label from their startup script.
            nid = self.provider.node_tags(pid).get("node_id", "")
            if nid in gcs_alive:
                return nid
            return gcs_by_provider.get(pid, "")

        # 3) ALLOCATED -> RAY_RUNNING once every host registered alive.
        for inst in im.instances((ALLOCATED,)):
            hexes = [gcs_hex_of(p) for p in inst.provider_ids]
            if all(h and gcs_alive.get(h) for h in hexes):
                im.update_instance(inst.instance_id, RAY_RUNNING,
                                   gcs_node_ids=tuple(hexes))
                events.append(f"{inst.instance_id}: ray running")

        # 4) instances whose provider nodes vanished -> TERMINATED.
        for inst in im.instances((ALLOCATED, RAY_RUNNING, RAY_STOPPING)):
            if inst.provider_ids and not \
                    (set(inst.provider_ids) & alive_provider):
                im.update_instance(inst.instance_id, TERMINATED)
                events.append(f"{inst.instance_id}: provider gone")

        # 5) RAY_STOPPING: request the drain, then hand to TERMINATING
        # only once every host is idle (or gone) — terminating a node
        # with in-flight work would kill it instead of draining.
        for inst in im.instances((RAY_STOPPING,)):
            if gcs_request is not None:
                # Idempotent: the GCS marks the node draining; re-sending
                # across passes is harmless.
                for h in inst.gcs_node_ids:
                    gcs_request("drain_node", {"node_id_hex": h})
            drained = all(
                not gcs_alive.get(h, False) or gcs_idle.get(h, False)
                for h in inst.gcs_node_ids)
            if drained:
                im.update_instance(inst.instance_id, TERMINATING)

        # 6) TERMINATING: issue provider terminations.
        for inst in im.instances((TERMINATING,)):
            for pid in inst.provider_ids:
                if pid in alive_provider:
                    self.provider.terminate_node(pid)
            im.update_instance(inst.instance_id, TERMINATED)
            events.append(f"{inst.instance_id}: terminated")
        return {"events": events}


# ---------------------------------------------------------------------------
# the v2 engine
# ---------------------------------------------------------------------------

class AutoscalerV2:
    """update() = scheduler decision + reconcile, driven by the same GCS
    autoscaler-state RPC as v1 (drop-in alternative engine)."""

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 gcs_request):
        self.config = config
        self.provider = provider
        self.gcs_request = gcs_request
        self.im = InstanceManager()
        self.reconciler = Reconciler(provider, config.node_types)
        self._idle_since: Dict[str, float] = {}

    def _demand_shapes(self, state: dict) -> List[Dict[str, float]]:
        return demand_shapes(state)

    def update(self) -> dict:
        state = self.gcs_request("get_autoscaler_state", {})
        active = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)
        counts: Dict[str, int] = {}
        for inst in self.im.instances(active):
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
        bins = [dict(n["available"]) for n in state["nodes"].values()
                if n["alive"]]
        # Capacity already requested but not yet registered with the GCS
        # counts as supply too (prevents double-launch across passes).
        for inst in self.im.instances((QUEUED, REQUESTED, ALLOCATED)):
            bins.append(dict(
                self.config.node_types[inst.node_type].resources))
        to_launch = compute_scaling_decision(
            self._demand_shapes(state), self.config.node_types, bins,
            counts)
        for node_type, n in to_launch.items():
            for _ in range(min(n, self.config.max_launch_batch)):
                self.im.add_instance(node_type)
        self._scale_down_idle(state)
        result = self.reconciler.reconcile(self.im, state,
                                           self.gcs_request)
        self.im.prune()
        result["instances"] = {
            i.instance_id: i.state for i in self.im.instances()}
        return result

    def _scale_down_idle(self, state: dict):
        now = time.time()
        if self._demand_shapes(state):
            self._idle_since.clear()
            return
        gcs_by_hex = {
            (nid.hex() if hasattr(nid, "hex") else str(nid)): info
            for nid, info in state["nodes"].items()}

        def idle(hexid: str) -> bool:
            n = gcs_by_hex.get(hexid)
            return n is not None and node_is_idle(n)

        counts: Dict[str, int] = {}
        for inst in self.im.instances((RAY_RUNNING,)):
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
        for inst in self.im.instances((RAY_RUNNING,)):
            t = self.config.node_types[inst.node_type]
            if not inst.gcs_node_ids or \
                    not all(idle(h) for h in inst.gcs_node_ids):
                self._idle_since.pop(inst.instance_id, None)
                continue
            first = self._idle_since.setdefault(inst.instance_id, now)
            if (now - first >= self.config.idle_timeout_s
                    and counts.get(inst.node_type, 0) > t.min_workers):
                self.im.update_instance(inst.instance_id, RAY_STOPPING)
                counts[inst.node_type] -= 1
                self._idle_since.pop(inst.instance_id, None)
