"""Node providers: pluggable machinery the autoscaler uses to launch and
terminate nodes.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider ABC) and
python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237
(FakeMultiNodeProvider — fake nodes for tests without a cloud). TPU-first
deltas: a "node" is a TPU host (or a whole slice when `slice_hosts` > 1 in
the node type), so create_node must gang-create every host of a slice.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """ABC. Provider node ids are provider-scoped opaque strings."""

    def __init__(self, provider_config: Optional[dict] = None):
        self.provider_config = provider_config or {}

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def internal_ip(self, provider_node_id: str) -> str:
        return ""


class FakeMultiNodeProvider(NodeProvider):
    """Launches in-process raylets against a live GCS — the test provider.

    Each "node" is a Raylet started on the caller's event loop (same
    mechanism as cluster_utils.Cluster.add_node), so autoscaler behavior is
    testable with zero cloud access and real scheduling.
    """

    def __init__(self, gcs_address: str, config, session_dir: str = "",
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        super().__init__()
        self.gcs_address = gcs_address
        self.config = config
        self.session_dir = session_dir
        self.loop = loop
        self._nodes: Dict[str, object] = {}     # provider id -> Raylet
        self._tags: Dict[str, Dict[str, str]] = {}

    def _run(self, coro):
        if self.loop is None:
            raise RuntimeError("FakeMultiNodeProvider needs a background loop")
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            raise RuntimeError(
                "provider must not be driven from its own event loop")
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(60)

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        from ray_tpu._private.raylet import Raylet
        created = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
            resources = dict(node_config.get("resources") or {"CPU": 1.0})
            resources.setdefault("memory", 2.0 * 1024**3)
            resources.setdefault("object_store_memory", 128.0 * 1024**2)

            async def _start():
                raylet = Raylet(self.config, self.gcs_address,
                                self.session_dir, resources=resources,
                                labels={"ray_tpu.io/node-type": node_type},
                                object_store_memory=int(
                                    resources["object_store_memory"]),
                                node_name=pid)
                await raylet.start()
                return raylet

            raylet = self._run(_start())
            self._nodes[pid] = raylet
            self._tags[pid] = {"node_type": node_type,
                               "launched_at": str(time.time()),
                               "node_id": raylet.node_id.hex()}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        raylet = self._nodes.pop(provider_node_id, None)
        self._tags.pop(provider_node_id, None)
        if raylet is None:
            return

        async def _stop():
            await raylet.stop()

        self._run(_stop())

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        return dict(self._tags.get(provider_node_id, {}))

    def node_id_of(self, provider_node_id: str) -> str:
        return self._tags.get(provider_node_id, {}).get("node_id", "")


class TPUPodProvider(NodeProvider):
    """GCE TPU-VM provider skeleton: slice-granular create/delete via the
    TPU API. Gated: requires GCP credentials + the cloud SDK at runtime
    (not available in CI), so every method raises with instructions.

    Reference analogue: python/ray/autoscaler/_private/gcp/node_provider.py;
    TPU specifics per python/ray/_private/accelerators/tpu.py (slice
    topology, TPU-<type>-head resource).
    """

    def __init__(self, provider_config: Optional[dict] = None):
        super().__init__(provider_config)
        raise RuntimeError(
            "TPUPodProvider requires GCP credentials and the TPU API; "
            "configure provider_config={project, zone, accelerator_type} "
            "on a GCE deployment. Use FakeMultiNodeProvider for local "
            "testing.")
