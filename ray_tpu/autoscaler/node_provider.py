"""Node providers: pluggable machinery the autoscaler uses to launch and
terminate nodes.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider ABC) and
python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237
(FakeMultiNodeProvider — fake nodes for tests without a cloud). TPU-first
deltas: a "node" is a TPU host (or a whole slice when `slice_hosts` > 1 in
the node type), so create_node must gang-create every host of a slice.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """ABC. Provider node ids are provider-scoped opaque strings."""

    def __init__(self, provider_config: Optional[dict] = None):
        self.provider_config = provider_config or {}

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def internal_ip(self, provider_node_id: str) -> str:
        return ""

    def preemption_notices(self) -> List[str]:
        """Provider node ids facing imminent reclamation (spot/preemptible
        capacity). The autoscaler polls this each reconcile pass and
        converts notices into GCS drains with a tight deadline, so the
        planned-loss path (object migration, uncharged actor restarts)
        runs inside the cloud's warning window. Default: none."""
        return []


class FakeMultiNodeProvider(NodeProvider):
    """Launches in-process raylets against a live GCS — the test provider.

    Each "node" is a Raylet started on the caller's event loop (same
    mechanism as cluster_utils.Cluster.add_node), so autoscaler behavior is
    testable with zero cloud access and real scheduling.
    """

    def __init__(self, gcs_address: str, config, session_dir: str = "",
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        super().__init__()
        self.gcs_address = gcs_address
        self.config = config
        self.session_dir = session_dir
        self.loop = loop
        self._nodes: Dict[str, object] = {}     # provider id -> Raylet
        self._tags: Dict[str, Dict[str, str]] = {}
        self._preempt_announced: List[str] = []

    def _run(self, coro):
        if self.loop is None:
            raise RuntimeError("FakeMultiNodeProvider needs a background loop")
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            raise RuntimeError(
                "provider must not be driven from its own event loop")
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(60)

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        from ray_tpu._private.raylet import Raylet
        created = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
            resources = dict(node_config.get("resources") or {"CPU": 1.0})
            resources.setdefault("memory", 2.0 * 1024**3)
            resources.setdefault("object_store_memory", 128.0 * 1024**2)

            async def _start():
                raylet = Raylet(self.config, self.gcs_address,
                                self.session_dir, resources=resources,
                                labels={"ray_tpu.io/node-type": node_type},
                                object_store_memory=int(
                                    resources["object_store_memory"]),
                                node_name=pid)
                await raylet.start()
                return raylet

            raylet = self._run(_start())
            self._nodes[pid] = raylet
            self._tags[pid] = {"node_type": node_type,
                               "launched_at": str(time.time()),
                               "node_id": raylet.node_id.hex()}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        raylet = self._nodes.pop(provider_node_id, None)
        self._tags.pop(provider_node_id, None)
        if raylet is None:
            return

        async def _stop():
            await raylet.stop()

        self._run(_stop())

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        return dict(self._tags.get(provider_node_id, {}))

    def node_id_of(self, provider_node_id: str) -> str:
        return self._tags.get(provider_node_id, {}).get("node_id", "")

    def announce_preemption(self, provider_node_id: str):
        """Test hook: fake a cloud preemption notice for this node."""
        if provider_node_id not in self._preempt_announced:
            self._preempt_announced.append(provider_node_id)

    def preemption_notices(self) -> List[str]:
        return [p for p in self._preempt_announced if p in self._nodes]


class TPUPodProvider(NodeProvider):
    """GCE TPU-VM provider: node create/list/delete against the Cloud TPU
    REST API (tpu.googleapis.com/v2), slice-granular via the autoscaler's
    gang launches.

    Reference analogue: python/ray/autoscaler/_private/gcp/node_provider.py
    + gcp/tpu_command_runner.py; TPU specifics per
    python/ray/_private/accelerators/tpu.py (slice topology,
    TPU-<type>-head resource).

    All HTTP goes through an injectable ``transport(method, url, body) ->
    (status, json_dict)`` so the provider is fully unit-testable with a
    mocked API. Without an injected transport, a default one is built
    LAZILY on first use and authenticates via the GCE metadata server —
    the runtime credential gate: constructing the provider off-GCE works
    (config validation, tests), but real calls fail with instructions
    unless credentials exist.
    """

    API = "https://tpu.googleapis.com/v2"
    # TPU node states that count as live capacity.
    LIVE_STATES = ("CREATING", "READY", "RESTARTING", "REPAIRING")

    def __init__(self, provider_config: Optional[dict] = None,
                 transport=None, sleep=time.sleep):
        super().__init__(provider_config)
        cfg = self.provider_config
        missing = [k for k in ("project", "zone") if not cfg.get(k)]
        if missing:
            raise ValueError(
                f"TPUPodProvider provider_config missing {missing}; "
                "needs at least {project, zone} plus per-node-type "
                "accelerator_type/runtime_version")
        self.cluster_name = cfg.get("cluster_name", "ray-tpu")
        self._parent = (f"projects/{cfg['project']}/"
                        f"locations/{cfg['zone']}")
        self._transport = transport
        self._sleep = sleep
        self._poll_s = float(cfg.get("operation_poll_s", 5.0))
        self._op_timeout_s = float(cfg.get("operation_timeout_s", 900.0))
        # Node-listing cache: one reconcile pass calls
        # non_terminated_nodes/node_tags/internal_ip O(nodes) times; serve
        # them from one LIST instead of N+1 GETs per pass.
        self._list_cache: Optional[List[dict]] = None
        self._list_cache_t = 0.0
        self._list_cache_ttl = float(cfg.get("list_cache_ttl_s", 2.0))

    # ---- transport / auth (the runtime gate) -------------------------

    def _fetch_token(self) -> str:
        import json
        import urllib.request
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return json.loads(r.read())["access_token"]
        except Exception as e:  # noqa: BLE001
            raise RuntimeError(
                "TPUPodProvider needs GCP credentials: run on GCE with a "
                f"service account (metadata server unreachable: {e!r}) or "
                "inject a transport") from e

    def _default_transport(self):
        import json
        import urllib.error
        import urllib.request

        def transport(method: str, url: str, body: Optional[dict] = None):
            req = urllib.request.Request(
                url, method=method,
                data=None if body is None else json.dumps(body).encode(),
                headers={"Authorization": f"Bearer {self._fetch_token()}",
                         "Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    return r.status, json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                try:
                    detail = json.loads(e.read() or b"{}")
                except Exception:  # noqa: BLE001
                    detail = {}
                return e.code, detail

        return transport

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        if self._transport is None:
            self._transport = self._default_transport()
        status, data = self._transport(method, f"{self.API}/{path}", body)
        if status >= 400:
            raise RuntimeError(
                f"TPU API {method} {path} failed ({status}): "
                f"{data.get('error', data)}")
        return data

    def _wait_operation(self, op: dict) -> dict:
        deadline = time.monotonic() + self._op_timeout_s
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"TPU operation {op.get('name')} timed out")
            self._sleep(self._poll_s)
            op = self._request("GET", op["name"])
        if "error" in op:
            raise RuntimeError(f"TPU operation failed: {op['error']}")
        return op.get("response", {})

    # ---- NodeProvider API --------------------------------------------

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        cfg = self.provider_config
        type_cfg = (cfg.get("node_types") or {}).get(node_type, {})
        accel = (node_config.get("accelerator_type")
                 or type_cfg.get("accelerator_type")
                 or cfg.get("accelerator_type"))
        runtime = (node_config.get("runtime_version")
                   or type_cfg.get("runtime_version")
                   or cfg.get("runtime_version", "tpu-ubuntu2204-base"))
        if not accel:
            raise ValueError(
                f"no accelerator_type for node type {node_type!r}")
        created = []
        ops = []
        try:
            # Fire every create first, then wait the operations together —
            # a gang of N hosts pays one operation latency, not N, and the
            # reconcile pass isn't frozen serially.
            for _ in range(count):
                node_id = f"ray-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
                body = {
                    "acceleratorType": accel,
                    "runtimeVersion": runtime,
                    "labels": {
                        "ray-cluster": self.cluster_name,
                        "ray-node-type": node_type,
                    },
                }
                if cfg.get("network"):
                    body["networkConfig"] = {"network": cfg["network"]}
                if cfg.get("startup_script"):
                    # {node_id} in the script lets the VM start its raylet
                    # with `--labels ray_tpu.io/provider-id=<id>` so the
                    # autoscaler can correlate it with its GCS node.
                    body["metadata"] = {"startup-script":
                                        cfg["startup_script"].replace(
                                            "{node_id}", node_id)}
                ops.append(self._request(
                    "POST", f"{self._parent}/nodes?nodeId={node_id}", body))
                created.append(node_id)
            for op in ops:
                self._wait_operation(op)
        except Exception:
            # Compensate a partial gang: nodes the caller never learns
            # about must not keep running (and billing).
            for node_id in created:
                try:
                    self._request("DELETE",
                                  f"{self._parent}/nodes/{node_id}")
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            self._invalidate_listing()
            raise
        self._invalidate_listing()
        return created

    def _invalidate_listing(self):
        self._list_cache = None

    def _list_nodes(self) -> List[dict]:
        now = time.monotonic()
        if (self._list_cache is not None
                and now - self._list_cache_t < self._list_cache_ttl):
            return self._list_cache
        out = []
        page = self._request("GET", f"{self._parent}/nodes")
        out.extend(page.get("nodes", []))
        while page.get("nextPageToken"):
            page = self._request(
                "GET",
                f"{self._parent}/nodes?pageToken={page['nextPageToken']}")
            out.extend(page.get("nodes", []))
        self._list_cache = out
        self._list_cache_t = now
        return out

    def _get_node(self, provider_node_id: str) -> dict:
        for n in self._list_nodes():
            if self._short_id(n) == provider_node_id:
                return n
        raise RuntimeError(f"TPU node {provider_node_id!r} not found")

    @staticmethod
    def _short_id(node: dict) -> str:
        return node.get("name", "").rsplit("/", 1)[-1]

    def non_terminated_nodes(self) -> List[str]:
        return [
            self._short_id(n) for n in self._list_nodes()
            if n.get("labels", {}).get("ray-cluster") == self.cluster_name
            and n.get("state") in self.LIVE_STATES
        ]

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        n = self._get_node(provider_node_id)
        labels = n.get("labels", {})
        # The GCS node id isn't knowable from the cloud API; correlation
        # happens in the autoscaler via the ray_tpu.io/provider-id label
        # the VM's raylet registers with (see create_node startup script).
        return {
            "node_type": labels.get("ray-node-type", ""),
            "node_id": "",
            "state": n.get("state", ""),
            "launched_at": n.get("createTime", ""),
        }

    def terminate_node(self, provider_node_id: str) -> None:
        op = self._request(
            "DELETE", f"{self._parent}/nodes/{provider_node_id}")
        self._wait_operation(op)
        self._invalidate_listing()

    def internal_ip(self, provider_node_id: str) -> str:
        eps = self._get_node(provider_node_id).get("networkEndpoints") or []
        return eps[0].get("ipAddress", "") if eps else ""

    def preemption_notices(self) -> List[str]:
        """Preemption-notice source for GCE preemptible/spot TPU capacity.

        Two channels, both polled by the autoscaler's reconcile pass:
        - the TPU API node state: a node the control plane already flagged
          (PREEMPTED / TERMINATED while we still track it) is reported so
          the drain at least runs the uncharged-recovery bookkeeping;
        - an injectable ``preemption_hook() -> [provider_node_id]`` in
          provider_config — in production a sidecar watching each VM's
          metadata server preemption endpoint; in tests a plain closure.
        """
        out: List[str] = []
        hook = self.provider_config.get("preemption_hook")
        if callable(hook):
            try:
                out.extend(hook())
            except Exception:  # noqa: BLE001 — a bad hook must not
                pass           # break the reconcile loop
        for n in self._list_nodes():
            if (n.get("labels", {}).get("ray-cluster") == self.cluster_name
                    and n.get("state") in ("PREEMPTED", "TERMINATED")):
                pid = self._short_id(n)
                if pid not in out:
                    out.append(pid)
        return out


class K8sPodProvider(NodeProvider):
    """Kubernetes provider: each ray_tpu node is a pod, created/listed/
    deleted through the apiserver REST API — the KubeRay-equivalent layer.

    Reference analogue: python/ray/autoscaler/_private/kuberay/
    node_provider.py (KubeRayNodeProvider: pods with ray.io/* labels,
    patched replica counts). TPU-first deltas: node types may declare GKE
    TPU podslices (`tpu_accelerator` + `tpu_topology` + `chips_per_host`) —
    create_node then emits pods with `google.com/tpu` resource limits and
    the GKE nodeSelectors, gang-creating `slice_hosts` pods that share a
    `ray.io/slice-id` label so a multi-host slice schedules (and dies)
    together.

    All HTTP goes through an injectable ``transport(method, url, body) ->
    (status, json_dict)``; without one, a default transport authenticates
    with the in-cluster service-account token (the runtime credential
    gate — constructing the provider off-cluster works for tests/config
    validation, real calls raise with instructions).
    """

    LIVE_PHASES = ("Pending", "Running")

    def __init__(self, provider_config: Optional[dict] = None,
                 transport=None):
        super().__init__(provider_config)
        cfg = self.provider_config
        self.namespace = cfg.get("namespace", "default")
        self.cluster_name = cfg.get("cluster_name", "ray-tpu")
        self.api_server = cfg.get(
            "api_server", "https://kubernetes.default.svc")
        self.image = cfg.get("image", "")
        self._transport = transport
        self._list_cache: Optional[List[dict]] = None
        self._list_cache_t = 0.0
        self._list_cache_ttl = float(cfg.get("list_cache_ttl_s", 2.0))

    # ---- transport / auth (the runtime gate) -------------------------

    _SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def _default_transport(self):
        import json as _json
        import ssl
        import urllib.error
        import urllib.request

        token_path = self.provider_config.get(
            "token_path", f"{self._SA_DIR}/token")
        ca_path = self.provider_config.get(
            "ca_cert_path", f"{self._SA_DIR}/ca.crt")
        try:
            with open(token_path) as f:
                token = f.read().strip()
        except OSError as e:
            raise RuntimeError(
                "K8sPodProvider needs in-cluster credentials: run inside a "
                f"pod with a service account ({token_path} unreadable: "
                f"{e!r}) or inject a transport") from e
        ctx = ssl.create_default_context(
            cafile=ca_path if os.path.exists(ca_path) else None)
        if not os.path.exists(ca_path):
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE

        def transport(method: str, url: str, body: Optional[dict] = None):
            req = urllib.request.Request(
                url, method=method,
                data=None if body is None else _json.dumps(body).encode(),
                headers={"Authorization": f"Bearer {token}",
                         "Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60,
                                            context=ctx) as r:
                    return r.status, _json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                try:
                    detail = _json.loads(e.read() or b"{}")
                except Exception:  # noqa: BLE001
                    detail = {}
                return e.code, detail

        return transport

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        if self._transport is None:
            self._transport = self._default_transport()
        status, data = self._transport(
            method, f"{self.api_server}{path}", body)
        if status >= 400:
            raise RuntimeError(
                f"k8s API {method} {path} failed ({status}): "
                f"{data.get('message', data)}")
        return data

    # ---- pod manifest ------------------------------------------------

    def _pod_manifest(self, name: str, node_type: str, type_cfg: dict,
                      slice_id: str = "") -> dict:
        cfg = self.provider_config
        labels = {
            "ray.io/cluster": self.cluster_name,
            "ray.io/node-type": node_type,
        }
        if slice_id:
            labels["ray.io/slice-id"] = slice_id
        container: dict = {
            "name": "ray-node",
            "image": type_cfg.get("image") or self.image or "ray-tpu:latest",
            "command": type_cfg.get("command") or [
                "python", "-m", "ray_tpu.scripts.cli", "start",
                "--address", cfg.get("head_address", "auto"),
                "--provider-id", name, "--block"],
            "resources": {"limits": {}, "requests": {}},
        }
        spec: dict = {"restartPolicy": "Never", "containers": [container]}
        req = container["resources"]["requests"]
        lim = container["resources"]["limits"]
        if type_cfg.get("cpu"):
            req["cpu"] = str(type_cfg["cpu"])
        if type_cfg.get("memory"):
            req["memory"] = str(type_cfg["memory"])
        chips = int(type_cfg.get("chips_per_host", 0))
        if chips:
            # GKE TPU podslice: google.com/tpu limits + the two GKE
            # nodeSelectors route the pod onto the right slice nodepool.
            lim["google.com/tpu"] = str(chips)
            req["google.com/tpu"] = str(chips)
            sel = spec.setdefault("nodeSelector", {})
            if type_cfg.get("tpu_accelerator"):
                sel["cloud.google.com/gke-tpu-accelerator"] = \
                    type_cfg["tpu_accelerator"]
            if type_cfg.get("tpu_topology"):
                sel["cloud.google.com/gke-tpu-topology"] = \
                    type_cfg["tpu_topology"]
        if type_cfg.get("node_selector"):
            spec.setdefault("nodeSelector", {}).update(
                type_cfg["node_selector"])
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": name, "labels": labels,
                         "namespace": self.namespace},
            "spec": spec,
        }
        # Deep-merge a user pod_template last so anything above is
        # overridable without this provider growing a knob per field.
        template = type_cfg.get("pod_template") or cfg.get("pod_template")
        if template:
            pod = _deep_merge(template, pod)
        return pod

    # ---- NodeProvider API --------------------------------------------

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> List[str]:
        cfg = self.provider_config
        type_cfg = dict((cfg.get("node_types") or {}).get(node_type, {}))
        type_cfg.update(node_config or {})
        slice_hosts = int(type_cfg.get("slice_hosts", 1))
        created: List[str] = []
        try:
            for _ in range(count):
                slice_id = (f"{self.cluster_name}-"
                            f"{uuid.uuid4().hex[:8]}")
                for host in range(slice_hosts):
                    name = (f"ray-{slice_id}-{host}"
                            if slice_hosts > 1 else f"ray-{slice_id}")
                    self._request(
                        "POST",
                        f"/api/v1/namespaces/{self.namespace}/pods",
                        self._pod_manifest(
                            name, node_type, type_cfg,
                            slice_id=slice_id if slice_hosts > 1 else ""))
                    created.append(name)
        except Exception:
            # Compensate a partial gang — pods the autoscaler never
            # learns about must not keep running.
            for name in created:
                try:
                    self._request(
                        "DELETE",
                        f"/api/v1/namespaces/{self.namespace}/pods/{name}")
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            self._invalidate_listing()
            raise
        self._invalidate_listing()
        return created

    def _invalidate_listing(self):
        self._list_cache = None

    def _list_pods(self) -> List[dict]:
        now = time.monotonic()
        if (self._list_cache is not None
                and now - self._list_cache_t < self._list_cache_ttl):
            return self._list_cache
        sel = f"ray.io%2Fcluster%3D{self.cluster_name}"
        out: List[dict] = []
        page = self._request(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods"
                   f"?labelSelector={sel}")
        out.extend(page.get("items", []))
        while page.get("metadata", {}).get("continue"):
            page = self._request(
                "GET", f"/api/v1/namespaces/{self.namespace}/pods"
                       f"?labelSelector={sel}"
                       f"&continue={page['metadata']['continue']}")
            out.extend(page.get("items", []))
        self._list_cache = out
        self._list_cache_t = now
        return out

    def _get_pod(self, provider_node_id: str) -> dict:
        for p in self._list_pods():
            if p.get("metadata", {}).get("name") == provider_node_id:
                return p
        raise RuntimeError(f"pod {provider_node_id!r} not found")

    def non_terminated_nodes(self) -> List[str]:
        return [
            p["metadata"]["name"] for p in self._list_pods()
            if p.get("status", {}).get("phase", "Pending")
            in self.LIVE_PHASES
        ]

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        p = self._get_pod(provider_node_id)
        labels = p.get("metadata", {}).get("labels", {})
        return {
            "node_type": labels.get("ray.io/node-type", ""),
            "node_id": "",
            "state": p.get("status", {}).get("phase", ""),
            "slice_id": labels.get("ray.io/slice-id", ""),
            "launched_at": p.get("metadata", {})
                            .get("creationTimestamp", ""),
        }

    def terminate_node(self, provider_node_id: str) -> None:
        # Terminating one host of a multi-host slice kills the gang — a
        # podslice is an atomic scheduling unit (mirrors TPU slice
        # semantics and KubeRay worker-group scaling).
        try:
            tags = self.node_tags(provider_node_id)
        except RuntimeError:
            tags = {}
        victims = [provider_node_id]
        slice_id = tags.get("slice_id", "")
        if slice_id:
            victims = [
                p["metadata"]["name"] for p in self._list_pods()
                if p.get("metadata", {}).get("labels", {})
                    .get("ray.io/slice-id") == slice_id
            ]
        for name in victims:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{self.namespace}/pods/{name}")
        self._invalidate_listing()

    def internal_ip(self, provider_node_id: str) -> str:
        return self._get_pod(provider_node_id).get(
            "status", {}).get("podIP", "")


def _deep_merge(base: dict, override: dict) -> dict:
    """Recursive dict merge: override wins on scalars, merges on dicts."""
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
