"""Autoscaler: demand-driven node provisioning (SURVEY.md §2.3 autoscaler
row; reference python/ray/autoscaler/)."""

from ray_tpu.autoscaler.autoscaler import (AutoscalerConfig, NodeTypeConfig,
                                           StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              NodeProvider, TPUPodProvider)
from ray_tpu.autoscaler.monitor import Monitor, make_gcs_request
from ray_tpu.autoscaler.commands import (ClusterLauncher,
                                         create_or_update_cluster,
                                         load_cluster_config,
                                         teardown_cluster)
from ray_tpu.autoscaler.v2 import (AutoscalerV2, InstanceManager,
                                   Reconciler)

__all__ = [
    "AutoscalerConfig", "NodeTypeConfig", "StandardAutoscaler",
    "AutoscalerV2", "InstanceManager", "Reconciler",
    "NodeProvider", "FakeMultiNodeProvider", "TPUPodProvider",
    "Monitor", "make_gcs_request",
    "ClusterLauncher", "create_or_update_cluster", "load_cluster_config",
    "teardown_cluster",
]
