"""Cluster launcher: config-driven cluster lifecycle (`ray_tpu up/down`).

Reference parity: python/ray/autoscaler/_private/commands.py
(create_or_update_cluster/teardown_cluster behind `ray up`/`ray down`) +
the cluster-config YAML schema (autoscaler/ray-schema.json, trimmed to
the fields this stack uses):

    cluster_name: demo
    max_workers: 8
    provider:
      type: fake            # or: tpu_pod (GCE Cloud TPU API, gated)
      ...provider-specific keys...
    head_node_type: head
    available_node_types:
      head:
        resources: {CPU: 4}
        max_workers: 0
      worker:
        resources: {CPU: 2}
        min_workers: 1
        max_workers: 4

`up` starts the head in THIS process, builds the configured NodeProvider,
and runs the StandardAutoscaler monitor so min_workers come up and demand
scales the rest. `down` terminates every provider node and stops the head.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_REQUIRED = ("provider", "available_node_types", "head_node_type")


def load_cluster_config(path_or_dict) -> Dict[str, Any]:
    if isinstance(path_or_dict, dict):
        cfg = dict(path_or_dict)
    else:
        import yaml
        with open(path_or_dict) as f:
            cfg = yaml.safe_load(f)
    for key in _REQUIRED:
        if key not in cfg:
            raise ValueError(f"cluster config missing {key!r}")
    head_type = cfg["head_node_type"]
    if head_type not in cfg["available_node_types"]:
        raise ValueError(f"head_node_type {head_type!r} not in "
                         f"available_node_types")
    cfg.setdefault("cluster_name", "ray_tpu")
    cfg.setdefault("max_workers", 8)
    return cfg


def _build_provider(cfg: dict, gcs_address: str, session_dir: str):
    provider_cfg = dict(cfg["provider"])
    # The cluster name scopes provider-side node labels: without it two
    # clusters in one project would share the default label and `down`
    # on one would terminate the other's nodes.
    provider_cfg.setdefault("cluster_name", cfg["cluster_name"])
    ptype = provider_cfg.get("type", "fake")
    if ptype == "fake":
        from ray_tpu._private import worker_api
        from ray_tpu._private.config import get_config
        from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider
        return FakeMultiNodeProvider(gcs_address, get_config(), session_dir,
                                     loop=worker_api._state.loop)
    if ptype == "tpu_pod":
        from ray_tpu.autoscaler.node_provider import TPUPodProvider
        return TPUPodProvider(provider_cfg)
    if ptype == "k8s":
        from ray_tpu.autoscaler.node_provider import K8sPodProvider
        return K8sPodProvider(provider_cfg)
    raise ValueError(f"unknown provider type {ptype!r}")


def _autoscaler_node_types(cfg: dict) -> dict:
    """Launcher YAML node types -> AutoscalerConfig node-type dicts."""
    out = {}
    for name, t in cfg["available_node_types"].items():
        if name == cfg["head_node_type"]:
            continue
        out[name] = {
            "resources": t.get("resources", {}),
            "min_workers": t.get("min_workers", 0),
            "max_workers": t.get("max_workers", cfg["max_workers"]),
            "slice_hosts": t.get("slice_hosts", 1),
        }
    return out


class ClusterLauncher:
    """Handle for a launched cluster: head + provider + monitor."""

    def __init__(self, config: dict):
        self.config = config
        self.cluster = None       # cluster_utils.Cluster hosting the head
        self.provider = None
        self.monitor = None
        self.gcs_address = ""

    def start(self) -> str:
        from ray_tpu._private import worker_api
        from ray_tpu.autoscaler import (AutoscalerConfig, Monitor,
                                        StandardAutoscaler,
                                        make_gcs_request)
        from ray_tpu.cluster_utils import Cluster

        head_type = self.config["available_node_types"][
            self.config["head_node_type"]]
        head_res = dict(head_type.get("resources", {}))
        num_cpus = head_res.pop("CPU", 2)
        num_tpus = head_res.pop("TPU", 0)
        self.cluster = Cluster(
            initialize_head=True,
            head_node_args={"num_cpus": num_cpus, "num_tpus": num_tpus,
                            "resources": head_res})
        self.gcs_address = self.cluster.gcs_address
        try:
            self.provider = _build_provider(self.config, self.gcs_address,
                                            self.cluster.session_dir)
            as_config = AutoscalerConfig.from_dict({
                "node_types": _autoscaler_node_types(self.config),
                "max_workers": self.config["max_workers"],
            })
            gcs_request = make_gcs_request(self.gcs_address,
                                           worker_api._state.loop)
            scaler = StandardAutoscaler(as_config, self.provider,
                                        gcs_request)
            scaler.gcs_request("get_autoscaler_state", {})  # mark active
            self.monitor = Monitor(scaler)
            self.monitor.start()
        except Exception:
            # Never leak a running head (GCS + raylet on the daemon
            # loop) behind a failed bring-up.
            self.teardown()
            raise
        logger.info("cluster %s up: GCS at %s",
                    self.config["cluster_name"], self.gcs_address)
        return self.gcs_address

    def teardown(self):
        if self.monitor is not None:
            # full join: an in-flight update() may still be creating a
            # node; sweeping before it finishes would leak that node
            self.monitor.stop(join_timeout=None)
        if self.provider is not None:
            for pid in list(self.provider.non_terminated_nodes()):
                try:
                    self.provider.terminate_node(pid)
                except Exception:
                    logger.exception("terminate %s failed", pid)
        if self.cluster is not None:
            self.cluster.shutdown()


def create_or_update_cluster(path_or_dict) -> ClusterLauncher:
    """`ray up`: bring the cluster up; returns the live handle."""
    launcher = ClusterLauncher(load_cluster_config(path_or_dict))
    launcher.start()
    return launcher


def teardown_cluster(path_or_dict,
                     launcher: Optional[ClusterLauncher] = None) -> int:
    """`ray down`: terminate provider nodes (and the head when the
    in-process launcher handle is given). Returns the number of provider
    nodes terminated."""
    if launcher is not None:
        n = len(launcher.provider.non_terminated_nodes()) \
            if launcher.provider is not None else 0
        launcher.teardown()
        return n
    cfg = load_cluster_config(path_or_dict)
    # Out-of-process teardown only reaches provider-managed nodes.
    provider = _build_provider(cfg, gcs_address="", session_dir="")
    nodes = list(provider.non_terminated_nodes())
    for pid in nodes:
        provider.terminate_node(pid)
    return len(nodes)
