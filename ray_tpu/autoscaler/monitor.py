"""Autoscaler monitor: the live control loop around StandardAutoscaler.

Reference: python/ray/autoscaler/_private/monitor.py:126 — a process on the
head node that wakes periodically, reads GCS state, and reconciles. Here it
is a daemon thread (the GCS client rides the shared background event loop).
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)


def make_gcs_request(gcs_address: str, loop: asyncio.AbstractEventLoop):
    """Synchronous GCS request bridge for the autoscaler/thread context."""
    from ray_tpu._private import rpc
    holder = {}

    async def _conn():
        c = holder.get("c")
        if c is None or c.closed:
            holder["c"] = c = await rpc.connect(gcs_address)
        return c

    def request(method: str, payload: dict):
        async def _r():
            return await (await _conn()).request(method, payload)
        return asyncio.run_coroutine_threadsafe(_r(), loop).result(30)

    return request


class Monitor:
    def __init__(self, autoscaler, interval_s: Optional[float] = None):
        self.autoscaler = autoscaler
        self.interval_s = (interval_s if interval_s is not None
                           else autoscaler.config.update_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ray_tpu-autoscaler")
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler update failed")

    def stop(self, join_timeout: Optional[float] = 5.0):
        """join_timeout=None waits for the in-flight update to finish —
        teardown needs that, or a create completing after the node sweep
        leaks a node."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=join_timeout)
