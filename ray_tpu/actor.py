"""Actor façade: ActorClass / ActorHandle / ActorMethod.

Reference parity: python/ray/actor.py (ActorClass :544, ActorHandle :1193,
ActorMethod :113, max_restarts/max_task_retries :147). Async actors are
detected from coroutine methods; handles serialize into tasks and reconnect
via the GCS actor table on deserialization.
"""

from __future__ import annotations

import hashlib
import inspect
from typing import Any, Dict, Optional

from ray_tpu._private import worker_api
from ray_tpu._private.ids import ActorID
from ray_tpu.remote_function import _resolve_scheduling, _resources_from_options


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group
        # Spec template for the steady-state call fast path (see
        # RemoteFunction): invariants of THIS (handle, method, options)
        # triple. .options() products are new ActorMethod instances, so
        # an option change never reuses a stale template.
        self._spec_template = None

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name,
            opts.get("num_returns", self._num_returns),
            opts.get("concurrency_group", self._concurrency_group))

    def _build_template(self, core):
        from ray_tpu._private.common import TaskSpec, TaskSpecTemplate
        proto = TaskSpec(
            task_id=None, job_id=core.job_id, name=self._name,
            args=[], num_returns=self._num_returns,
            owner_address=core.address, owner_worker_id=core.worker_id,
            actor_id=self._handle._actor_id, method_name=self._name,
            max_retries=self._handle._max_task_retries,
            concurrency_group=self._concurrency_group,
        )
        tmpl = TaskSpecTemplate(proto, token=(core, None))
        self._spec_template = tmpl
        return tmpl

    def remote(self, *args, **kwargs):
        core = worker_api.get_core()
        num_returns = self._num_returns
        streaming = num_returns == "streaming"
        if not streaming and not worker_api._on_core_loop(core):
            # Steady-state fast path: stamp task id + seq + args onto the
            # cached template; no per-call option resolution.
            tmpl = self._spec_template
            if tmpl is None or tmpl.token[0] is not core:
                tmpl = self._build_template(core)
            refs = core.submit_actor_task_templated(tmpl, args, kwargs)
            return refs[0] if num_returns == 1 else refs
        if streaming:
            num_returns = 0
        if worker_api._on_core_loop(core):
            # Async-actor context: submission is synchronous bookkeeping +
            # deferred dispatch, legal on the loop thread.
            refs = core.submit_actor_task_local(
                self._handle._actor_id, self._name, args, kwargs,
                num_returns=num_returns,
                max_task_retries=self._handle._max_task_retries,
                concurrency_group=self._concurrency_group,
                is_generator=streaming)
        else:
            # User thread: reserve ids synchronously, dispatch fire-and-forget
            # (no blocking cross-thread round trip per call).
            refs = core.submit_actor_task_threadsafe(
                self._handle._actor_id, self._name, args, kwargs,
                num_returns=num_returns,
                max_task_retries=self._handle._max_task_retries,
                concurrency_group=self._concurrency_group,
                is_generator=streaming)
        if num_returns == 1 or streaming:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; use "
            f"'.{self._name}.remote()'.")

    def __getstate__(self):
        # Process-local template (token holds the live CoreWorker): never
        # rides a pickle — rebuilt on first call wherever this lands.
        d = dict(self.__dict__)
        d["_spec_template"] = None
        return d

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (reference: dag_node.py bind)."""
        from ray_tpu.dag.dag_node import ClassMethodNode
        return ClassMethodNode(self, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_names=None,
                 max_task_retries: int = 0, class_name: str = "",
                 method_options: Optional[Dict[str, dict]] = None):
        self._actor_id = actor_id
        self._method_names = method_names or []
        self._max_task_retries = max_task_retries
        self._class_name = class_name
        # Per-method defaults from the @ray_tpu.method decorator
        # (num_returns, concurrency_group).
        self._method_options = method_options or {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        mo = self._method_options.get(name, {})
        method = ActorMethod(self, name,
                             num_returns=mo.get("num_returns", 1),
                             concurrency_group=mo.get("concurrency_group",
                                                      ""))
        # Memoize: `h.method.remote()` in a loop was allocating a fresh
        # ActorMethod (and losing its spec template) per call. Instance
        # attribute hits bypass __getattr__ entirely from now on;
        # __reduce__ pickles the handle from its explicit fields, so the
        # cache never rides the wire.
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._method_names,
                                  self._max_task_retries, self._class_name,
                                  self._method_options))

    @classmethod
    def _from_actor_info(cls, info):
        spec = getattr(info, "creation_spec", None)
        return cls(info.actor_id, class_name=info.class_name,
                   method_options=getattr(spec, "method_options", None)
                   if spec is not None else None)


def _rebuild_handle(actor_id, method_names, max_task_retries, class_name,
                    method_options=None):
    return ActorHandle(actor_id, method_names, max_task_retries, class_name,
                       method_options)


class ActorClass:
    def __init__(self, cls: type, options: Optional[dict] = None):
        self._cls = cls
        self._options = options or {}
        self._class_id: Optional[str] = None
        self.__name__ = cls.__name__
        # Per-class invariants resolved once per core (launch storms call
        # .remote() in a tight loop; the inspect scans and option
        # resolution were measurable per-create costs): (core, kwargs).
        self._create_cache: Optional[tuple] = None
        self._methods: Optional[list] = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self.__name__}' cannot be instantiated directly; "
            f"use '{self.__name__}.remote()'.")

    def options(self, **new_options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(new_options)
        ac = ActorClass(self._cls, merged)
        ac._class_id = self._class_id
        return ac

    def __getstate__(self):
        """The create cache holds (core_worker, kwargs) — process-local
        and unpicklable (live asyncio state). An ActorClass captured in
        a remote closure (e.g. a worker that spawns its own actors)
        must ship WITHOUT it; the remote process rebuilds its own."""
        state = self.__dict__.copy()
        state["_create_cache"] = None
        return state

    def _is_async(self) -> bool:
        return any(inspect.iscoroutinefunction(m)
                   for _, m in inspect.getmembers(self._cls,
                                                  inspect.isfunction))

    def remote(self, *args, **kwargs):
        client = worker_api.client_mode()
        if client is not None:
            return client.create_actor(self, args, kwargs, self._options)
        opts = self._options
        name = opts.get("name", "")
        if opts.get("get_if_exists") and name:
            return self._get_or_create(name, args, kwargs)
        return self._create(args, kwargs)

    def _get_or_create(self, name: str, args, kwargs) -> ActorHandle:
        """options(name=..., get_if_exists=True): reference parity with
        ray's atomic get-or-create (python/ray/actor.py GetOrCreate)."""
        import time
        from ray_tpu._private.worker_api import get_actor
        namespace = self._options.get("namespace")
        try:
            return get_actor(name, namespace)
        except Exception:
            pass
        try:
            return self._create(args, kwargs)
        except Exception:
            # Lost the creation race; wait for the winner's actor to
            # register (worker startup can take seconds on a loaded node).
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    return get_actor(name, namespace)
                except Exception:
                    time.sleep(0.1)
            raise

    def _create(self, args, kwargs) -> ActorHandle:
        core = worker_api.get_core()
        on_loop = worker_api._on_core_loop(core)
        if self._class_id is None:
            from ray_tpu._private.serialization import dumps_function
            data = dumps_function(self._cls)
            self._class_id = "actor:" + hashlib.sha1(data).hexdigest()
        export = None
        if not worker_api._state.exported_functions.get(self._class_id):
            if on_loop:
                # Deferred: chained before GCS registration inside
                # create_actor_local's background task.
                export = (self._cls, self._class_id)
            else:
                worker_api._call_on_core_loop(
                    core, core.export_function(self._cls, self._class_id), 30)
            worker_api._state.exported_functions[self._class_id] = True
        opts = self._options
        create_kwargs = self._resolve_create_kwargs(core, opts)
        if on_loop:
            actor_id, _done = core.create_actor_local(
                self._class_id, args, kwargs, export=export, **create_kwargs)
        else:
            actor_id = None
            if not create_kwargs["name"]:
                # Fire-and-forget reservation on this thread (a storm of
                # anonymous creates pays no per-call loop round trip);
                # None => an arg needs the loop, take the blocking path.
                actor_id = core.create_actor_threadsafe(
                    self._class_id, args, kwargs, **create_kwargs)
            if actor_id is None:
                actor_id = worker_api._call_on_core_loop(
                    core, core.create_actor(self._class_id, args, kwargs,
                                            **create_kwargs), None)
        return ActorHandle(actor_id, self._methods,
                           opts.get("max_task_retries", 0), self.__name__,
                           create_kwargs["method_options"])

    def _resolve_create_kwargs(self, core, opts) -> dict:
        cached = self._create_cache
        if cached is not None and cached[0] is core:
            return cached[1]
        is_async = self._is_async()
        max_concurrency = opts.get(
            "max_concurrency", 1000 if is_async else 1)
        resources = _resources_from_options(opts) if (
            opts.get("num_cpus") is not None or opts.get("num_tpus") is not None
            or opts.get("num_gpus") is not None or opts.get("resources")
        ) else {"CPU": 0.0}
        # Ray default: actors reserve 0 CPU for scheduling unless specified
        # (1 CPU only for creation); we use 0 to allow many actors per node.
        namespace = opts.get("namespace")
        if namespace is None:
            namespace = worker_api._state.namespace
        # Concurrency groups: accept {name: limit} or the reference's list
        # form [{"name": ..., "max_concurrency": ...}].
        cgs = opts.get("concurrency_groups")
        if isinstance(cgs, (list, tuple)):
            cgs = {g["name"]: int(g["max_concurrency"]) for g in cgs}
        members = inspect.getmembers(self._cls, inspect.isfunction)
        method_options = {
            n: dict(m.__ray_tpu_method_options__)
            for n, m in members
            if getattr(m, "__ray_tpu_method_options__", None)}
        self._methods = [n for n, _ in members if not n.startswith("__")]
        create_kwargs = dict(
            class_name=self.__name__,
            resources=resources,
            scheduling=_resolve_scheduling(opts),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=max_concurrency,
            is_async=is_async,
            name=opts.get("name", ""),
            namespace=namespace,
            lifetime=opts.get("lifetime", ""),
            runtime_env=worker_api.resolve_runtime_env(
                opts.get("runtime_env")),
            concurrency_groups=cgs,
            execute_out_of_order=bool(opts.get("execute_out_of_order",
                                               False)),
            method_options=method_options,
        )
        self._create_cache = (core, create_kwargs)
        return create_kwargs
