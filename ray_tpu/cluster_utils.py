"""Multi-node-in-one-process test cluster.

Reference parity: python/ray/cluster_utils.py — the single highest-leverage
test asset in the reference (SURVEY.md §4): N raylets sharing one GCS so
multi-node scheduling, spillback, object transfer, and failure handling are
testable on one host. Here the raylets run on the driver's background event
loop (real TCP servers; worker processes are real subprocesses), so tests can
kill a "node" by stopping its raylet.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ray_tpu._private import worker_api
from ray_tpu._private.config import Config, set_config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.node import new_session_dir
from ray_tpu._private.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None,
                 system_config: Optional[dict] = None):
        self.config = Config.load(system_config)
        set_config(self.config)
        self.session_dir = new_session_dir(self.config)
        self.gcs: Optional[GcsServer] = None
        self.raylets: List[Raylet] = []
        self.gcs_address = ""
        worker_api._ensure_loop()
        self._loop = worker_api._state.loop
        self._run(self._start_gcs())
        if initialize_head:
            self.add_node(**(head_node_args or {}), is_head=True)

    def _run(self, coro, timeout: float = 60):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(timeout)

    async def _start_gcs(self):
        self.gcs = GcsServer(self.config, self.session_dir)
        self.gcs_address = await self.gcs.start()

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 256 * 1024**2,
                 is_head: bool = False, node_name: str = "",
                 slice_id: str = "", zone: str = "") -> Raylet:
        """slice_id groups fake nodes into one TPU slice fault domain:
        draining (or losing) any member gang-drains the whole group.
        zone marks the DCN locality domain (pod / cloud zone): migration
        off a draining slice prefers same-zone replacement nodes."""
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res.setdefault("TPU", float(num_tpus))
        res.setdefault("memory", 2.0 * 1024**3)
        res.setdefault("object_store_memory", float(object_store_memory))

        async def _add():
            raylet = Raylet(self.config, self.gcs_address, self.session_dir,
                            resources=res, labels=labels, is_head=is_head,
                            object_store_memory=object_store_memory,
                            node_name=node_name or f"node{len(self.raylets)}",
                            slice_id=slice_id, zone=zone)
            await raylet.start()
            return raylet

        raylet = self._run(_add())
        self.raylets.append(raylet)
        return raylet

    def remove_node(self, raylet: Raylet, graceful: bool = False):
        """Kill a node (ungraceful: simulates node failure)."""
        if graceful:
            # Full two-phase drain with a short deadline, then tear down.
            self.drain_node(raylet, deadline_s=5.0, grace_s=0.1, wait=True)
            return

        async def _remove():
            await raylet.stop()
            # Let the health checker notice, or force-mark dead now.
            await self.gcs._mark_node_dead(raylet.node_id, "node removed")
        self._run(_remove())
        self.raylets.remove(raylet)

    def drain_node(self, raylet: Raylet, deadline_s: float = 5.0,
                   grace_s: float = 0.5, wait: bool = True):
        """Two-phase graceful drain (test API for the drain protocol).

        Issues DrainNode on the GCS: the node stops taking new work, its
        primary object copies migrate to live peers, its actors restart
        elsewhere without charging max_restarts, and it is marked dead at
        the deadline (or as soon as it reports idle). wait=True blocks
        until the node is dead and then stops the raylet; wait=False
        returns right after the notice (the notice-then-kill race is the
        caller's to script — see util.chaos.PreemptionKiller).
        """
        async def _drain():
            await self.gcs.rpc_drain_node(None, {
                "node_id": raylet.node_id, "deadline_s": deadline_s,
                "grace_s": grace_s, "wait": wait})
        self._run(_drain(), timeout=deadline_s + 30)
        if wait:
            async def _stop():
                await raylet.stop()
            self._run(_stop())
            if raylet in self.raylets:
                self.raylets.remove(raylet)

    def restart_gcs(self):
        """Kill the GCS process-equivalent and restart it on the SAME
        address, restoring the session snapshot (head fault tolerance).
        Raylets and workers re-register via their reconnect loops."""
        host, port = self.gcs_address.rsplit(":", 1)

        async def _restart():
            await self.gcs.stop()
            self.gcs = GcsServer(self.config, self.session_dir)
            await self.gcs.start(host, int(port), restore=True)

        self._run(_restart())

    def connect(self, namespace: str = ""):
        """Attach a driver to this cluster."""
        import ray_tpu
        ray_tpu.init(address=self.gcs_address, namespace=namespace)

    def wait_for_nodes(self, timeout: float = 10):
        import time
        import ray_tpu
        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(alive) >= len(self.raylets):
                return
            time.sleep(0.05)
        raise TimeoutError("nodes did not come up")

    def shutdown(self):
        import ray_tpu
        ray_tpu.shutdown()

        async def _stop():
            for raylet in self.raylets:
                try:
                    await raylet.stop()
                except Exception:
                    pass
            if self.gcs:
                await self.gcs.stop()
        self._run(_stop())
        self.raylets.clear()
