"""SampleBatch: columnar trajectory storage.

Reference parity: rllib/policy/sample_batch.py:99 (standard keys, concat,
minibatch iteration). Columns are numpy arrays; a batch converts to a jax
pytree with one device_put at the learner boundary.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
NEXT_OBS = "next_obs"
LOGPS = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"
# Recurrent-model columns (reference: SampleBatch "state_in_*" keys +
# the seq_lens machinery; here sequences are fixed-length fragments).
DONE_PREV = "done_prev"
STATE_IN_H = "state_in_h"
STATE_IN_C = "state_in_c"


class SampleBatch(dict):
    """dict[str, np.ndarray] with batch helpers."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        rng = np.random.RandomState(seed)
        idx = rng.permutation(len(self))
        return SampleBatch({k: v[idx] for k, v in self.items()})

    def minibatches(self, minibatch_size: int,
                    num_epochs: int = 1,
                    seed: Optional[int] = None) -> Iterator["SampleBatch"]:
        n = len(self)
        rng = np.random.RandomState(seed)
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n - minibatch_size + 1, minibatch_size):
                sel = idx[start:start + minibatch_size]
                yield SampleBatch({k: v[sel] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})


def concat_samples(batches: List[SampleBatch]) -> SampleBatch:
    batches = [b for b in batches if len(b)]
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([b[k] for b in batches])
                        for k in keys})


BOOTSTRAP_VALUES = "bootstrap_values"


def compute_gae(batch: SampleBatch, last_value: float, gamma: float,
                lam: float) -> SampleBatch:
    """Generalized advantage estimation over one rollout fragment.

    Reference parity: rllib/evaluation/postprocessing.py
    (compute_advantages). Episode boundaries inside the fragment cut the
    recursion; truncated (not terminated) steps bootstrap from
    batch["bootstrap_values"] — V(s_{t+1}) computed by the env runner
    BEFORE the env reset — and the fragment tail bootstraps from
    last_value.
    """
    rewards = batch[REWARDS]
    values = batch[VF_PREDS]
    terminateds = batch[TERMINATEDS]
    truncateds = batch.get(TRUNCATEDS, np.zeros_like(terminateds))
    bootstrap = batch.get(BOOTSTRAP_VALUES, np.zeros_like(values))
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    last_gae = 0.0
    for t in reversed(range(n)):
        if terminateds[t]:
            delta = rewards[t] - values[t]
            last_gae = delta
        elif truncateds[t]:
            delta = rewards[t] + gamma * bootstrap[t] - values[t]
            last_gae = delta
        else:
            next_v = last_value if t == n - 1 else values[t + 1]
            delta = rewards[t] + gamma * next_v - values[t]
            last_gae = delta + gamma * lam * last_gae
        adv[t] = last_gae
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = (adv + values).astype(np.float32)
    return batch


class MultiAgentBatch:
    """Per-policy SampleBatches plus the env-step count they came from.

    Reference parity: rllib/policy/sample_batch.py:1338 (MultiAgentBatch).
    `policy_batches` maps policy id -> SampleBatch; `env_steps` counts
    environment steps (agents stepping simultaneously share one env step),
    while agent_steps() sums per-agent transitions.
    """

    def __init__(self, policy_batches: dict, env_steps: int):
        self.policy_batches = dict(policy_batches)
        self.count = int(env_steps)

    def env_steps(self) -> int:
        return self.count

    def agent_steps(self) -> int:
        return sum(len(b) for b in self.policy_batches.values())

    def __len__(self):
        return self.count

    @staticmethod
    def wrap_as_needed(batch, env_steps: int) -> "MultiAgentBatch":
        if isinstance(batch, MultiAgentBatch):
            return batch
        return MultiAgentBatch({"default_policy": batch}, env_steps)

    @staticmethod
    def concat_samples(batches: list) -> "MultiAgentBatch":
        merged: dict = {}
        steps = 0
        for mb in batches:
            steps += mb.env_steps()
            for pid, b in mb.policy_batches.items():
                merged.setdefault(pid, []).append(b)
        return MultiAgentBatch(
            {pid: concat_samples(bs) for pid, bs in merged.items()}, steps)

    def __repr__(self):
        sizes = {p: len(b) for p, b in self.policy_batches.items()}
        return f"MultiAgentBatch(env_steps={self.count}, policies={sizes})"
