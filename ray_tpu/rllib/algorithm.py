"""Algorithm base + fluent AlgorithmConfig.

Reference parity: rllib/algorithms/algorithm.py:202 (Algorithm extends the
Tune Trainable so `tune.Tuner(PPO)` works) and algorithm_config.py:125
(fluent .environment()/.env_runners()/.training() builder).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Type

import numpy as np

import ray_tpu
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env = "CartPole-v1"
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 200
        self.gamma = 0.99
        self.lr = 5e-4
        self.train_batch_size = 0  # 0 => runners * envs * fragment
        self.minibatch_size = 128
        self.num_epochs = 8
        self.hidden = (64, 64)
        # Full catalog model config dict (fcnet_hiddens / conv_filters /
        # use_lstm / lstm_cell_size); None -> legacy default MLP.
        self.model: Optional[Dict[str, Any]] = None
        self.seed = 0
        # Multi-agent (set via .multi_agent()); declared here so the plain
        # dict config path (Tune param_space) round-trips them too.
        self.policies: Optional[List[str]] = None
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None
        # Connector pipelines (reference: rllib/connectors/): extra
        # env->module obs connectors and module->env action connectors
        # appended to each runner's default pipeline.
        self.obs_connectors: Optional[List[Any]] = None
        self.action_connectors: Optional[List[Any]] = None
        self.extra: Dict[str, Any] = {}

    # -- fluent sections (reference: AlgorithmConfig.environment etc.) ----
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None,
                    obs_connectors=None,
                    action_connectors=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if obs_connectors is not None:
            self.obs_connectors = list(obs_connectors)
        if action_connectors is not None:
            self.action_connectors = list(action_connectors)
        return self

    def training(self, *, gamma=None, lr=None, train_batch_size=None,
                 minibatch_size=None, num_epochs=None,
                 model=None, **extra) -> "AlgorithmConfig":
        if gamma is not None:
            self.gamma = gamma
        if lr is not None:
            self.lr = lr
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        if num_epochs is not None:
            self.num_epochs = num_epochs
        if model is not None:
            self.model = dict(model)
            if "fcnet_hiddens" in model:
                self.hidden = tuple(model["fcnet_hiddens"])
        self.extra.update(extra)
        return self

    def multi_agent(self, *, policies=None,
                    policy_mapping_fn=None) -> "AlgorithmConfig":
        """Declare policies + the agent->policy mapping (reference:
        algorithm_config.py multi_agent())."""
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def is_multi_agent(self) -> bool:
        return bool(getattr(self, "policies", None))

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        cls = self.algo_class
        if cls is None:
            raise ValueError("no algo_class bound to this config")
        return cls(config=self)

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items()
             if k not in ("algo_class",)}
        return d


class Algorithm(Trainable):
    """Base: owns EnvRunner actors; subclasses implement training_step().

    As a tune.Trainable, config may be an AlgorithmConfig or a plain dict
    (Tune param_space path).
    """

    config_class: Type[AlgorithmConfig] = AlgorithmConfig
    # Algorithms whose learner builds through the model catalog set this;
    # others keep the legacy MLP even if a model config is present (their
    # learner's param layout must match the runner's).
    supports_model_config = False

    def __init__(self, config=None):
        if isinstance(config, AlgorithmConfig):
            self.algo_config = config
        else:
            self.algo_config = self.config_class(type(self))
            for k, v in (config or {}).items():
                if hasattr(self.algo_config, k):
                    setattr(self.algo_config, k, v)
                else:
                    self.algo_config.extra[k] = v
        self._iteration = 0
        super().__init__(self.algo_config.to_dict()
                         if isinstance(config, AlgorithmConfig)
                         else (config or {}))

    def _validate_config(self):
        """Driver-side config rejection BEFORE any actor spawns (a bad
        combo must fail with a clear error, not a traceback from inside
        a remote runner's jit trace). Subclasses extend via super()."""
        cfg = self.algo_config
        if cfg.model is not None and not self.supports_model_config:
            # fcnet_hiddens alone still maps onto the legacy MLP (the
            # base training() mirrors it into cfg.hidden); anything else
            # would be silently dropped — reject instead.
            dropped = set(cfg.model) - {"fcnet_hiddens"}
            if dropped:
                raise ValueError(
                    f"{type(self).__name__} does not support model "
                    f"config keys {sorted(dropped)} (only fcnet_hiddens "
                    f"maps onto its legacy network)")

    # -- Trainable API ------------------------------------------------------
    def setup(self, config: Dict[str, Any]):
        from ray_tpu.rllib.env import get_env_creator
        from ray_tpu.rllib.env_runner import EnvRunner, MultiAgentEnvRunner
        cfg = self.algo_config
        self._validate_config()
        # Resolve the env creator here (driver-side registry) so custom
        # registered envs work inside worker processes.
        creator = get_env_creator(cfg.env)
        if cfg.is_multi_agent:
            runner_cls = ray_tpu.remote(num_cpus=1)(MultiAgentEnvRunner)
            self.env_runners = [
                runner_cls.remote(creator, cfg.env_config,
                                  cfg.policies, cfg.policy_mapping_fn,
                                  num_envs=cfg.num_envs_per_env_runner,
                                  seed=cfg.seed + 1000 * i,
                                  hidden=cfg.hidden)
                for i in range(cfg.num_env_runners)
            ]
        else:
            runner_cls = ray_tpu.remote(num_cpus=1)(self._runner_class())
            extra = self._extra_runner_kwargs()
            self.env_runners = [
                runner_cls.remote(creator, cfg.env_config,
                                  cfg.num_envs_per_env_runner,
                                  seed=cfg.seed + 1000 * i,
                                  hidden=cfg.hidden,
                                  obs_connectors=cfg.obs_connectors,
                                  model=(cfg.model
                                         if self.supports_model_config
                                         else None),
                                  **extra)
                for i in range(cfg.num_env_runners)
            ]
        self._episode_rewards: List[float] = []
        self.build_learner()

    def _runner_class(self):
        """Rollout-actor class for the single-agent path; algorithms with
        a custom sampler (e.g. C51's expected-Q scoring) override this
        instead of copying setup()."""
        from ray_tpu.rllib.env_runner import EnvRunner
        return EnvRunner

    def _extra_runner_kwargs(self) -> Dict[str, Any]:
        return {}

    def build_learner(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        self._iteration += 1
        result = self.training_step()
        rewards = []
        for r in ray_tpu.get(
                [er.episode_rewards.remote() for er in self.env_runners]):
            rewards.extend(r)
        self._episode_rewards.extend(rewards)
        recent = self._episode_rewards[-100:]
        result.setdefault("episode_reward_mean",
                          float(np.mean(recent)) if recent else float("nan"))
        result.setdefault("episodes_total", len(self._episode_rewards))
        result.setdefault("training_iteration", self._iteration)
        return result

    def train(self) -> Dict[str, Any]:
        return self.step()

    def sample_all_runners(self) -> List:
        """Fan out one rollout per runner; returns refs (pipelining is the
        caller's choice)."""
        cfg = self.algo_config
        return [er.sample.remote(cfg.rollout_fragment_length, cfg.gamma,
                                 self.gae_lambda())
                for er in self.env_runners]

    def gae_lambda(self) -> float:
        return getattr(self.algo_config, "lambda_", 0.95)

    def broadcast_weights(self, params):
        ray_tpu.get([er.set_weights.remote(params)
                     for er in self.env_runners])

    def cleanup(self):
        for er in getattr(self, "env_runners", []):
            try:
                ray_tpu.kill(er)
            except Exception:
                pass

    def stop(self):
        self.cleanup()
