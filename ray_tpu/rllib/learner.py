"""PPO learner: one fused, jitted update step.

Reference parity: rllib/core/learner/learner.py:106 — but where the
reference runs a torch DDP loop, this is a single jit-compiled
loss+grad+apply on whatever backend hosts the learner (TPU when available).
Scaling across chips is a pmap/pjit axis, not a process group.

With a `model` config the learner builds through the catalog
(rllib/models/catalog.py parity): CNN torsos for image observations and,
with use_lstm, sequence training — fragments become [B, T] sequences, the
LSTM replays the sampler's exact carries (state_in columns) under
lax.scan with carry resets at episode boundaries, and minibatching
permutes whole sequences (the reference's max_seq_len padding machinery,
minus padding: fragments are fixed-length by construction).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.models import policy_value_apply, policy_value_init


class PPOLearner:
    def __init__(self, obs_dim: int, num_actions: int, *,
                 hidden=(64, 64), lr=5e-4, clip_param=0.2,
                 vf_coeff=0.5, entropy_coeff=0.0, seed=0,
                 obs_shape: Optional[Tuple[int, ...]] = None,
                 model: Optional[Dict[str, Any]] = None,
                 seq_len: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._clip_param = clip_param
        self._recurrent = False
        if model is not None:
            from ray_tpu.rllib.catalog import (ModelConfig,
                                               catalog_apply,
                                               catalog_apply_seq,
                                               catalog_init)
            mcfg = ModelConfig.from_dict(model)
            shape = tuple(obs_shape) if obs_shape else (obs_dim,)
            self.params = catalog_init(jax.random.PRNGKey(seed), shape,
                                       num_actions, mcfg)
            self._recurrent = mcfg.use_lstm
            self._seq_len = seq_len
            if self._recurrent and not seq_len:
                raise ValueError("recurrent model needs seq_len "
                                 "(= rollout_fragment_length)")
            if self._recurrent:
                seq_apply = (lambda p, o, d, s:
                             catalog_apply_seq(p, o, d, s, mcfg))
            else:
                fwd = lambda p, o: catalog_apply(p, o, mcfg)  # noqa: E731
        else:
            self.params = policy_value_init(
                jax.random.PRNGKey(seed), obs_dim, num_actions,
                hidden=tuple(hidden))
            fwd = policy_value_apply
        self.opt_state = self._optimizer.init(self.params)

        def ppo_terms(logits, values, actions, old_logp, adv, vtarg):
            """Shared PPO loss math over flat [N] tensors."""
            logp_all = jax.nn.log_softmax(logits)
            n = logits.shape[0]
            logp = logp_all[jnp.arange(n), actions]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg_loss = self._pg_loss(logp, old_logp, adv)
            vf_loss = ((values - vtarg) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "kl": (old_logp - logp).mean()}

        if self._recurrent:
            def loss_fn(params, batch):
                logits, values, _ = seq_apply(
                    params, batch[sb.OBS], batch[sb.DONE_PREV],
                    (batch[sb.STATE_IN_H], batch[sb.STATE_IN_C]))
                a = logits.shape[-1]
                return ppo_terms(
                    logits.reshape(-1, a), values.reshape(-1),
                    batch[sb.ACTIONS].reshape(-1),
                    batch[sb.LOGPS].reshape(-1),
                    batch[sb.ADVANTAGES].reshape(-1),
                    batch[sb.VALUE_TARGETS].reshape(-1))
        else:
            def loss_fn(params, batch):
                logits, values = fwd(params, batch[sb.OBS])
                return ppo_terms(logits, values, batch[sb.ACTIONS],
                                 batch[sb.LOGPS], batch[sb.ADVANTAGES],
                                 batch[sb.VALUE_TARGETS])

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._jit_update = jax.jit(update)

    def _pg_loss(self, logp, old_logp, adv):
        """Clipped-surrogate policy gradient (overridden by A2C with the
        vanilla advantage gradient)."""
        import jax.numpy as jnp
        ratio = jnp.exp(logp - old_logp)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1 - self._clip_param,
                       1 + self._clip_param) * adv
        return -jnp.minimum(pg1, pg2).mean()

    def update(self, batch, *, minibatch_size: int, num_epochs: int,
               seed=0) -> Dict[str, float]:
        import jax.numpy as jnp
        if self._recurrent:
            return self._update_recurrent(batch, minibatch_size,
                                          num_epochs, seed)
        metrics = {}
        needed = (sb.OBS, sb.ACTIONS, sb.LOGPS, sb.ADVANTAGES,
                  sb.VALUE_TARGETS)
        n_updates = 0
        for mb in batch.minibatches(minibatch_size, num_epochs, seed):
            jb = {k: jnp.asarray(mb[k]) for k in needed}
            self.params, self.opt_state, m = self._jit_update(
                self.params, self.opt_state, jb)
            n_updates += 1
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + float(v)
        if n_updates:
            metrics = {k: v / n_updates for k, v in metrics.items()}
        metrics["num_minibatch_updates"] = n_updates
        return metrics

    def _update_recurrent(self, batch, minibatch_size: int,
                          num_epochs: int, seed=0) -> Dict[str, float]:
        """Sequence-major update: [N] -> [B, T], permute sequences (never
        steps), replay carries from the fragment-start state_in rows."""
        import jax.numpy as jnp
        t = self._seq_len
        n = len(batch)
        if n % t:
            raise ValueError(f"batch of {n} not divisible by seq_len {t}")
        rows = n // t
        seq_cols = (sb.OBS, sb.ACTIONS, sb.LOGPS, sb.ADVANTAGES,
                    sb.VALUE_TARGETS, sb.DONE_PREV)
        arrs = {k: np.asarray(batch[k]).reshape(
            rows, t, *np.asarray(batch[k]).shape[1:]) for k in seq_cols}
        # state_in of each sequence = the sampler's carry at its 1st step.
        arrs[sb.STATE_IN_H] = np.asarray(
            batch[sb.STATE_IN_H]).reshape(rows, t, -1)[:, 0]
        arrs[sb.STATE_IN_C] = np.asarray(
            batch[sb.STATE_IN_C]).reshape(rows, t, -1)[:, 0]
        per_mb = max(1, minibatch_size // t)
        rng = np.random.RandomState(seed)
        metrics: Dict[str, float] = {}
        n_updates = 0
        for _ in range(num_epochs):
            order = rng.permutation(rows)
            for start in range(0, rows - per_mb + 1, per_mb):
                sel = order[start:start + per_mb]
                jb = {k: jnp.asarray(v[sel]) for k, v in arrs.items()}
                self.params, self.opt_state, m = self._jit_update(
                    self.params, self.opt_state, jb)
                n_updates += 1
                for k, v in m.items():
                    metrics[k] = metrics.get(k, 0.0) + float(v)
        if n_updates:
            metrics = {k: v / n_updates for k, v in metrics.items()}
        metrics["num_minibatch_updates"] = n_updates
        return metrics

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
