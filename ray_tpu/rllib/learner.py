"""PPO learner: one fused, jitted update step.

Reference parity: rllib/core/learner/learner.py:106 — but where the
reference runs a torch DDP loop, this is a single jit-compiled
loss+grad+apply on whatever backend hosts the learner (TPU when available).
Scaling across chips is a pmap/pjit axis, not a process group.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.models import policy_value_apply, policy_value_init


class PPOLearner:
    def __init__(self, obs_dim: int, num_actions: int, *,
                 hidden=(64, 64), lr=5e-4, clip_param=0.2,
                 vf_coeff=0.5, entropy_coeff=0.0, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._clip_param = clip_param
        self.params = policy_value_init(
            jax.random.PRNGKey(seed), obs_dim, num_actions,
            hidden=tuple(hidden))
        self.opt_state = self._optimizer.init(self.params)

        def loss_fn(params, batch):
            logits, values = policy_value_apply(params, batch[sb.OBS])
            logp_all = jax.nn.log_softmax(logits)
            n = logits.shape[0]
            logp = logp_all[jnp.arange(n), batch[sb.ACTIONS]]
            adv = batch[sb.ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg_loss = self._pg_loss(logp, batch[sb.LOGPS], adv)
            vf_loss = ((values - batch[sb.VALUE_TARGETS]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "kl": (batch[sb.LOGPS] - logp).mean()}

        def update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        import jax
        self._jit_update = jax.jit(update)

    def _pg_loss(self, logp, old_logp, adv):
        """Clipped-surrogate policy gradient (overridden by A2C with the
        vanilla advantage gradient)."""
        import jax.numpy as jnp
        ratio = jnp.exp(logp - old_logp)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1 - self._clip_param,
                       1 + self._clip_param) * adv
        return -jnp.minimum(pg1, pg2).mean()

    def update(self, batch, *, minibatch_size: int, num_epochs: int,
               seed=0) -> Dict[str, float]:
        import jax.numpy as jnp
        metrics = {}
        needed = (sb.OBS, sb.ACTIONS, sb.LOGPS, sb.ADVANTAGES,
                  sb.VALUE_TARGETS)
        n_updates = 0
        for mb in batch.minibatches(minibatch_size, num_epochs, seed):
            jb = {k: jnp.asarray(mb[k]) for k in needed}
            self.params, self.opt_state, m = self._jit_update(
                self.params, self.opt_state, jb)
            n_updates += 1
            for k, v in m.items():
                metrics[k] = metrics.get(k, 0.0) + float(v)
        if n_updates:
            metrics = {k: v / n_updates for k, v in metrics.items()}
        metrics["num_minibatch_updates"] = n_updates
        return metrics

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
