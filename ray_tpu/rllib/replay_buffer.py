"""Replay buffers (reference: rllib/utils/replay_buffers/)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class ReplayBuffer:
    """FIFO ring buffer of timesteps with uniform sampling."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._batches: List[SampleBatch] = []
        self._size = 0
        self._rng = np.random.RandomState(seed)

    def add(self, batch: SampleBatch):
        self._batches.append(batch)
        self._size += len(batch)
        while self._size > self.capacity and self._batches:
            old = self._batches[0]
            excess = self._size - self.capacity
            if len(old) <= excess:
                self._batches.pop(0)
                self._size -= len(old)
            else:
                self._batches[0] = old.slice(excess, len(old))
                self._size -= excess

    def __len__(self):
        return self._size

    def sample(self, num_items: int) -> SampleBatch:
        if not self._batches:
            return SampleBatch()
        merged = concat_samples(self._batches)
        self._batches = [merged]
        idx = self._rng.randint(0, len(merged), size=num_items)
        return SampleBatch({k: v[idx] for k, v in merged.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (reference:
    replay_buffers/prioritized_replay_buffer.py), simple array impl."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self._alpha = alpha
        self._prios: List[np.ndarray] = []
        self._max_prio = 1.0

    def add(self, batch: SampleBatch):
        super().add(batch)
        self._prios.append(np.full(len(batch), self._max_prio))
        total = sum(len(p) for p in self._prios)
        while total > self._size:
            excess = total - self._size
            if len(self._prios[0]) <= excess:
                total -= len(self._prios[0])
                self._prios.pop(0)
            else:
                self._prios[0] = self._prios[0][excess:]
                total -= excess

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        if not self._batches:
            return SampleBatch()
        merged = concat_samples(self._batches)
        self._batches = [merged]
        prios = np.concatenate(self._prios) if self._prios else \
            np.ones(len(merged))
        self._prios = [prios]
        p = prios[:len(merged)] ** self._alpha
        p = p / p.sum()
        idx = self._rng.choice(len(merged), size=num_items, p=p)
        weights = (len(merged) * p[idx]) ** (-beta)
        weights = weights / weights.max()
        out = SampleBatch({k: v[idx] for k, v in merged.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, prios: np.ndarray):
        if not self._prios:
            return
        arr = self._prios[0]
        arr[idx] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
