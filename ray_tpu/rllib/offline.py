"""Offline RL dataset I/O.

Reference parity: rllib/offline/ (JsonWriter json_writer.py, JsonReader
json_reader.py — the newline-delimited-JSON experience format used for
offline training and off-policy evaluation). Arrays serialize as nested
lists; a SampleBatch per line.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class JsonWriter:
    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.max_file_size = max_file_size
        self._index = 0
        self._fh = None
        self._bytes = 0

    def _rotate(self):
        if self._fh is not None:
            self._fh.close()
        name = os.path.join(self.path, f"output-{self._index:05d}.json")
        self._index += 1
        self._fh = open(name, "w")
        self._bytes = 0

    def write(self, batch: SampleBatch):
        if self._fh is None or self._bytes > self.max_file_size:
            self._rotate()
        rec = {k: np.asarray(v).tolist() for k, v in batch.items()}
        line = json.dumps(rec) + "\n"
        self._fh.write(line)
        self._bytes += len(line)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class JsonReader:
    def __init__(self, path: str, shuffle: bool = True,
                 seed: Optional[int] = None):
        if os.path.isdir(path):
            self.files = sorted(glob.glob(os.path.join(path, "*.json")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"no offline data under {path!r}")
        self._rng = np.random.RandomState(seed)
        self.shuffle = shuffle

    def read_all(self) -> SampleBatch:
        return concat_samples(list(self.iter_batches()))

    def iter_batches(self) -> Iterator[SampleBatch]:
        files = list(self.files)
        if self.shuffle:
            self._rng.shuffle(files)
        for f in files:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    yield SampleBatch({k: np.asarray(v)
                                       for k, v in rec.items()})

    def next(self) -> SampleBatch:
        """One uniformly random stored batch (reference: JsonReader.next)."""
        f = self.files[self._rng.randint(len(self.files))]
        with open(f) as fh:
            lines = [ln for ln in fh if ln.strip()]
        rec = json.loads(lines[self._rng.randint(len(lines))])
        return SampleBatch({k: np.asarray(v) for k, v in rec.items()})
