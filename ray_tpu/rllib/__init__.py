"""ray_tpu.rllib: reinforcement learning on the actor substrate.

Reference parity: rllib/ (Algorithm algorithms/algorithm.py:202,
AlgorithmConfig algorithms/algorithm_config.py:125, EnvRunner
env/env_runner.py:15, SampleBatch policy/sample_batch.py:99, PPO
algorithms/ppo/ppo.py:405, IMPALA algorithms/impala/impala.py:667).

TPU-first deltas: policies/learners are pure JAX (init/apply + jitted
update); rollout workers are CPU actors; the learner batch is a single
device_put + one fused jit step instead of a torch DDP loop.
"""

from ray_tpu.rllib.env import (CartPoleEnv, EnvSpec, MultiAgentEnv,
                               MultiCartPole, PendulumEnv, make_env,
                               register_env)
from ray_tpu.rllib.sample_batch import (MultiAgentBatch, SampleBatch,
                                        concat_samples)
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.impala import Impala, ImpalaConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.td3 import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.algorithms.pg import PG, PGConfig
from ray_tpu.rllib.algorithms.c51 import C51, C51Config
from ray_tpu.rllib.algorithms.apex import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.qrdqn import QRDQN, QRDQNConfig
from ray_tpu.rllib.algorithms.noisy import NoisyDQN, NoisyDQNConfig
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.offline import JsonReader, JsonWriter
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib import connectors

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "Impala",
    "ImpalaConfig", "APPO", "APPOConfig", "DQN", "DQNConfig", "BC",
    "BCConfig", "SAC", "SACConfig", "TD3", "TD3Config", "DDPG",
    "DDPGConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
    "A2C", "A2CConfig", "ES", "ESConfig", "ARS", "ARSConfig",
    "PG", "PGConfig", "C51", "C51Config", "ApexDQN", "ApexDQNConfig",
    "QRDQN", "QRDQNConfig", "NoisyDQN", "NoisyDQNConfig",
    "R2D2", "R2D2Config",
    "connectors", "EnvSpec", "CartPoleEnv",
    "PendulumEnv", "MultiAgentEnv", "MultiCartPole", "make_env",
    "register_env", "SampleBatch", "MultiAgentBatch", "concat_samples",
    "ReplayBuffer", "PrioritizedReplayBuffer", "JsonReader", "JsonWriter",
]
