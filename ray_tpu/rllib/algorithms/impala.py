"""IMPALA-style asynchronous PPO variant.

Reference parity: rllib/algorithms/impala/impala.py:667 — rollouts are
pipelined: the learner consumes whichever runner finishes first and
immediately re-dispatches it, so sampling and learning overlap and weight
broadcast is off the critical path. Off-policy drift is corrected by the
PPO clip (a lightweight stand-in for V-trace).
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.sample_batch import concat_samples


class ImpalaConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or Impala)
        self.num_batches_per_step = 4
        self.broadcast_interval = 2

    def training(self, *, num_batches_per_step=None,
                 broadcast_interval=None, **kw) -> "ImpalaConfig":
        super().training(**kw)
        if num_batches_per_step is not None:
            self.num_batches_per_step = num_batches_per_step
        if broadcast_interval is not None:
            self.broadcast_interval = broadcast_interval
        return self


class Impala(PPO):
    config_class = ImpalaConfig

    def setup(self, config):
        super().setup(config)
        cfg = self.algo_config
        # Prime the pipeline: one in-flight rollout per runner.
        self._inflight = {
            er.sample.remote(cfg.rollout_fragment_length, cfg.gamma,
                             self.gae_lambda()): er
            for er in self.env_runners
        }
        self._consumed_since_broadcast = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        steps = 0
        for _ in range(cfg.num_batches_per_step):
            done, _ = ray_tpu.wait(list(self._inflight.keys()),
                                   num_returns=1, timeout=60.0)
            if not done:
                break
            ref = done[0]
            runner = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            # Immediately re-dispatch the runner (async pipelining).
            self._inflight[runner.sample.remote(
                cfg.rollout_fragment_length, cfg.gamma,
                self.gae_lambda())] = runner
            m = self.learner.update(
                batch, minibatch_size=min(cfg.minibatch_size, len(batch)),
                num_epochs=1, seed=cfg.seed + self._iteration)
            steps += len(batch)
            metrics.update(m)
            self._consumed_since_broadcast += 1
            if self._consumed_since_broadcast >= cfg.broadcast_interval:
                # Off the critical path: fire-and-forget weight pushes.
                params = self.learner.get_weights()
                for er in self.env_runners:
                    er.set_weights.remote(params)
                self._consumed_since_broadcast = 0
        metrics["num_env_steps_sampled"] = steps
        return metrics
