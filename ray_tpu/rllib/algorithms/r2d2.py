"""R2D2: recurrent-replay distributed DQN.

Reference parity: rllib/algorithms/r2d2 (Kapturowski et al. 2019) — the
recurrent value-based family the feedforward DQN line cannot cover:

  - runners collect fixed-length SEQUENCES with the sampler's LSTM carry
    recorded at every step (the stored-state strategy; zero-state only at
    true episode starts);
  - the replay buffer holds whole sequences;
  - the learner replays each sequence under lax.scan (carry resets at
    in-sequence episode boundaries), computes double-Q TD targets from
    the WITHIN-sequence next step (q[t+1]); the final step of each
    sequence has no successor and is masked from the loss; an optional
    burn-in prefix rebuilds the carry without contributing loss.

TPU-first shape: both the online and target nets run as one scanned XLA
program over [B, T] — no per-step Python in the update.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class R2D2Config(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self.rollout_fragment_length = 16   # = training sequence length
        self.lstm_cell_size = 32
        self.burn_in = 0                    # carry-rebuild prefix steps
        self.train_batch_size = 16          # sequences per update

    def training(self, *, lstm_cell_size=None, burn_in=None,
                 **kw) -> "R2D2Config":
        super().training(**kw)
        if lstm_cell_size is not None:
            self.lstm_cell_size = lstm_cell_size
        if burn_in is not None:
            self.burn_in = burn_in
        return self


def _mcfg(cfg_hidden, lstm_cell_size, model):
    from ray_tpu.rllib.catalog import ModelConfig
    d = dict(model or {})
    d.setdefault("fcnet_hiddens", list(cfg_hidden))
    d["use_lstm"] = True
    d["lstm_cell_size"] = lstm_cell_size
    return ModelConfig.from_dict(d)


class R2D2Runner(EnvRunner):
    """Collects [n_envs, T] sequences with per-step stored carries and
    epsilon-greedy actions over the recurrent Q net."""

    def __init__(self, *args, lstm_cell_size=32, **kw):
        self._cell = lstm_cell_size
        super().__init__(*args, **kw)

    def _build_policy(self, seed, hidden, model):
        import jax
        from ray_tpu.rllib.catalog import (catalog_rq_apply_step,
                                           catalog_rq_init, obs_shape_of)
        e0 = self._envs[0]
        mcfg = _mcfg(hidden, self._cell, model)
        self._mcfg = mcfg
        self._params = catalog_rq_init(jax.random.PRNGKey(seed),
                                       obs_shape_of(e0), e0.num_actions,
                                       mcfg)
        z = np.zeros((len(self._envs), self._cell), np.float32)
        self._state = [z.copy(), z.copy()]
        self._jit_step = jax.jit(
            lambda p, o, s: catalog_rq_apply_step(p, o, s, mcfg))
        self._done_prev = np.zeros(len(self._envs), np.float32)

    def evaluate_return(self, params, episodes: int = 1,
                        max_steps: int = 500) -> float:
        """Greedy recurrent evaluation (the base class's shapes don't
        fit the (q, state) step signature)."""
        import jax.numpy as jnp
        from ray_tpu.rllib.env import make_env
        env = make_env(self._env_spec, self._env_config)
        total = 0.0
        for _ep in range(episodes):
            obs, _ = env.reset(seed=int(self._rng.randint(2 ** 31)))
            z = jnp.zeros((1, self._cell), jnp.float32)
            state = (z, z)
            for _ in range(max_steps):
                x = self._obs_conn(np.asarray(obs)[None], update=False)
                q, state = self._jit_step(params, x, state)
                obs, r, term, trunc, _ = env.step(
                    int(np.argmax(np.asarray(q)[0])))
                total += r
                if term or trunc:
                    break
        return total / episodes

    def sample_sequences(self, num_steps: int,
                         epsilon: float) -> SampleBatch:
        """One fragment per env: columns shaped [n_envs, T, ...] plus the
        fragment-start carry [n_envs, cell] and per-step done flags."""
        n_envs = len(self._envs)
        cols: Dict[str, List] = {k: [] for k in (
            sb.OBS, sb.ACTIONS, sb.REWARDS, "dones", sb.TERMINATEDS,
            sb.DONE_PREV)}
        h0, c0 = self._state[0].copy(), self._state[1].copy()
        for _t in range(num_steps):
            obs_arr = self._obs_conn(np.stack(self._obs))
            q, (h2, c2) = self._jit_step(self._params, obs_arr,
                                         tuple(self._state))
            q = np.asarray(q)
            h2, c2 = np.array(h2), np.array(c2)
            step = {k: [] for k in cols}
            for i, env in enumerate(self._envs):
                if self._rng.rand() < epsilon:
                    a = self._rng.randint(q.shape[-1])
                else:
                    a = int(np.argmax(q[i]))
                obs2, r, term, trunc, _ = env.step(a)
                step[sb.OBS].append(obs_arr[i])
                step[sb.ACTIONS].append(a)
                step[sb.REWARDS].append(r)
                step["dones"].append(float(term or trunc))
                step[sb.TERMINATEDS].append(float(term))
                step[sb.DONE_PREV].append(self._done_prev[i])
                self._ep_rewards[i] += r
                self._done_prev[i] = 0.0
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                    h2[i] = 0.0
                    c2[i] = 0.0
                    self._done_prev[i] = 1.0
                self._obs[i] = obs2
            for k, v in step.items():
                cols[k].append(v)
            self._state = [h2, c2]
        # [T, n_envs, ...] -> [n_envs, T, ...]
        out = {k: np.swapaxes(np.asarray(v), 0, 1)
               for k, v in cols.items()}
        out[sb.STATE_IN_H] = h0
        out[sb.STATE_IN_C] = c0
        return SampleBatch(out)


class R2D2Learner:
    def __init__(self, obs_shape, num_actions: int, *, hidden=(64, 64),
                 lstm_cell_size=32, lr=5e-4, gamma=0.99, double_q=True,
                 burn_in=0, model=None, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.catalog import (catalog_rq_apply_seq,
                                           catalog_rq_init)
        mcfg = _mcfg(hidden, lstm_cell_size, model)
        self._optimizer = optax.adam(lr)
        self.params = catalog_rq_init(jax.random.PRNGKey(seed), obs_shape,
                                      num_actions, mcfg)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.opt_state = self._optimizer.init(self.params)

        def loss_fn(params, target_params, batch, weights):
            state_in = (batch[sb.STATE_IN_H], batch[sb.STATE_IN_C])
            q, _ = catalog_rq_apply_seq(
                params, batch[sb.OBS], batch[sb.DONE_PREV], state_in,
                mcfg)                                     # [B, T, A]
            q_tgt, _ = catalog_rq_apply_seq(
                target_params, batch[sb.OBS], batch[sb.DONE_PREV],
                state_in, mcfg)
            bsz, t, _a = q.shape
            rows = jnp.arange(bsz)[:, None]
            ts = jnp.arange(t)[None, :]
            q_taken = q[rows, ts, batch[sb.ACTIONS]]      # [B, T]
            # Within-sequence targets from step t+1 (shift left).
            if double_q:
                a_next = jnp.argmax(q[:, 1:], -1)          # [B, T-1]
                v_next = q_tgt[:, 1:][rows, ts[:, :t - 1], a_next]
            else:
                v_next = q_tgt[:, 1:].max(-1)
            dones = batch["dones"][:, :t - 1]
            terms = batch[sb.TERMINATEDS][:, :t - 1]
            # done-but-truncated steps have no stored successor obs:
            # drop them from the loss alongside the final step. A
            # TERMINATED step needs no successor (target = reward).
            target = (batch[sb.REWARDS][:, :t - 1]
                      + gamma * (1.0 - terms) * v_next)
            # The step AFTER a done belongs to a new episode; its value
            # v_next is valid (carry was reset by done_prev) — but the
            # done step itself must not bootstrap across the boundary.
            trunc_no_succ = dones * (1.0 - terms)
            mask = jnp.ones((bsz, t - 1))
            mask = mask * (1.0 - trunc_no_succ)
            if burn_in > 0:
                mask = mask.at[:, :burn_in].set(0.0)
            td = (q_taken[:, :t - 1]
                  - jax.lax.stop_gradient(target)) * mask
            denom = jnp.maximum(mask.sum(), 1.0)
            # weights: per-SEQUENCE importance weights (sequence PER).
            loss = (weights[:, None] * td * td).sum() / denom
            # Per-sequence priority signal: mean |td|.
            per_seq = jnp.abs(td).sum(-1) / jnp.maximum(
                mask.sum(-1), 1.0)
            return loss, per_seq

        def update(params, target_params, opt_state, batch, weights):
            (loss, per), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch,
                                       weights)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, per

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, "dones", sb.TERMINATEDS,
               sb.DONE_PREV, sb.STATE_IN_H, sb.STATE_IN_C)}
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self.params, self.opt_state, loss, per = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights)
        return {"td_error": np.asarray(per), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class R2D2(DQN):
    config_class = R2D2Config
    supports_model_config = True   # catalog-built (torso choice applies)

    def _validate_config(self):
        # R2D2 IS the recurrent Q algorithm: skip DQN's no-LSTM check;
        # dueling heads and n-step returns are not implemented on the
        # sequence loss (targets come from the within-sequence t+1).
        if self.algo_config.dueling:
            raise ValueError("R2D2 does not support dueling heads")
        if self.algo_config.n_step != 1:
            raise ValueError("R2D2 bootstraps within the sequence; "
                             "n_step is not supported")

    def _runner_class(self):
        return R2D2Runner

    def _extra_runner_kwargs(self) -> Dict[str, Any]:
        return {"lstm_cell_size": self.algo_config.lstm_cell_size}

    def _make_q_learner(self, probe):
        from ray_tpu.rllib.catalog import obs_shape_of
        cfg = self.algo_config
        return R2D2Learner(
            obs_shape_of(probe), probe.num_actions, hidden=cfg.hidden,
            lstm_cell_size=cfg.lstm_cell_size, lr=cfg.lr,
            gamma=cfg.gamma, double_q=cfg.double_q, burn_in=cfg.burn_in,
            model=cfg.model, seed=cfg.seed)

    def build_learner(self):
        from ray_tpu.rllib.env import make_env
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = self._make_q_learner(probe)
        # Sequence replay: a SampleBatch row = one whole sequence, so
        # the step-denominated capacity knob converts to sequences
        # (same memory budget as the feedforward family).
        capacity = max(1, cfg.replay_buffer_capacity
                       // cfg.rollout_fragment_length)
        if cfg.prioritized_replay:
            from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer
            self.replay = PrioritizedReplayBuffer(capacity, seed=cfg.seed)
        else:
            self.replay = ReplayBuffer(capacity, seed=cfg.seed)
        self._steps_sampled = 0
        self._last_target_sync = 0
        self.broadcast_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        seq_batches = ray_tpu.get(
            [er.sample_sequences.remote(cfg.rollout_fragment_length, eps)
             for er in self.env_runners])
        batch = concat_samples(seq_batches)
        self.replay.add(batch)
        self._steps_sampled += (len(batch)
                                * cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {
            "epsilon": eps, "replay_sequences": len(self.replay),
            "num_env_steps_sampled": len(batch)
            * cfg.rollout_fragment_length}
        if len(self.replay) * cfg.rollout_fragment_length \
                >= cfg.learning_starts:
            losses = []
            for _ in range(cfg.updates_per_step):
                replayed = self.replay.sample(cfg.train_batch_size)
                m = self.learner.update(replayed)
                if cfg.prioritized_replay and "batch_indexes" in replayed:
                    self.replay.update_priorities(
                        replayed["batch_indexes"], m["td_error"] + 1e-6)
                losses.append(m["loss"])
            metrics["loss"] = float(np.mean(losses))
            self.broadcast_weights(self.learner.get_weights())
        if (self._steps_sampled - self._last_target_sync
                >= cfg.target_network_update_freq):
            self.learner.sync_target()
            self._last_target_sync = self._steps_sampled
        return metrics
