"""QR-DQN: quantile-regression distributional Q-learning.

Reference parity: the reference exposes quantile heads through its DQN
num_atoms/distributional config family (rllib/algorithms/dqn) — this is
the Dabney et al. 2018 formulation: the net emits N quantile estimates
of the return per action (no fixed support, unlike C51) and trains with
the quantile Huber loss. The whole pairwise [B, N, N] loss is one jitted
update.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig, NSTEP_GAMMAS
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.models import mlp_apply, policy_value_init
from ray_tpu.rllib.sample_batch import SampleBatch


class QRDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or QRDQN)
        self.n_quantiles = 32
        self.kappa = 1.0          # Huber threshold

    def training(self, *, n_quantiles=None, kappa=None,
                 **kw) -> "QRDQNConfig":
        super().training(**kw)
        if n_quantiles is not None:
            self.n_quantiles = n_quantiles
        if kappa is not None:
            self.kappa = kappa
        return self


def _quantile_init(seed, obs_dim, num_actions, n_quantiles, hidden):
    import jax
    return policy_value_init(jax.random.PRNGKey(seed), obs_dim,
                             num_actions * n_quantiles,
                             hidden=tuple(hidden))


class QRDQNRunner(EnvRunner):
    """Greedy scores = mean over the quantile estimates per action."""

    def __init__(self, *args, n_quantiles=32, **kw):
        self._n_quantiles = n_quantiles
        super().__init__(*args, **kw)

    def _build_policy(self, seed, hidden, model):
        import jax
        e0 = self._envs[0]
        n_act = e0.num_actions
        n_q = self._n_quantiles
        self._params = _quantile_init(seed, e0.observation_dim, n_act,
                                      n_q, hidden)

        def fwd(p, obs):
            theta = mlp_apply(p["pi"], obs).reshape(
                obs.shape[0], n_act, n_q)
            q = theta.mean(-1)
            return q, q.max(-1)

        self._jit_forward = jax.jit(fwd)


class QRDQNLearner:
    def __init__(self, obs_dim: int, num_actions: int, *, hidden=(64, 64),
                 lr=5e-4, gamma=0.99, n_quantiles=32, kappa=1.0,
                 double_q=True, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._gamma = gamma
        self.params = _quantile_init(seed, obs_dim, num_actions,
                                     n_quantiles, hidden)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.opt_state = self._optimizer.init(self.params)
        # Quantile midpoints tau_hat_i = (2i+1)/(2N).
        tau = (2 * jnp.arange(n_quantiles) + 1) / (2.0 * n_quantiles)

        def thetas(params, obs):
            return mlp_apply(params["pi"], obs).reshape(
                obs.shape[0], num_actions, n_quantiles)

        def loss_fn(params, target_params, batch, weights):
            n = batch[sb.OBS].shape[0]
            rows = jnp.arange(n)
            th = thetas(params, batch[sb.OBS])[rows, batch[sb.ACTIONS]]
            next_t = thetas(target_params, batch[sb.NEXT_OBS])
            sel = thetas(params, batch[sb.NEXT_OBS]) if double_q \
                else next_t
            a_next = sel.mean(-1).argmax(-1)
            next_q = next_t[rows, a_next]                      # [B, N]
            not_done = (1.0 - batch[sb.TERMINATEDS].astype(
                jnp.float32))[:, None]
            target = jax.lax.stop_gradient(
                batch[sb.REWARDS][:, None]
                + batch[NSTEP_GAMMAS][:, None] * not_done * next_q)
            # Pairwise TD errors u_ij = target_j - theta_i -> [B, N, N].
            u = target[:, None, :] - th[:, :, None]
            huber = jnp.where(
                jnp.abs(u) <= kappa, 0.5 * u * u,
                kappa * (jnp.abs(u) - 0.5 * kappa))
            # Quantile weighting |tau_i - 1{u<0}| applied per theta row.
            w = jnp.abs(tau[None, :, None] - (u < 0).astype(jnp.float32))
            per_sample = (w * huber).mean(-1).sum(-1)          # [B]
            return (weights * per_sample).mean(), per_sample

        def update(params, target_params, opt_state, batch, weights):
            (loss, per), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch,
                                       weights)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, per

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
               sb.TERMINATEDS)}
        jb[NSTEP_GAMMAS] = (jnp.asarray(batch[NSTEP_GAMMAS])
                            if NSTEP_GAMMAS in batch
                            else jnp.full(len(batch), self._gamma,
                                          jnp.float32))
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self.params, self.opt_state, loss, per = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights)
        return {"td_error": np.asarray(per), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class QRDQN(DQN):
    config_class = QRDQNConfig
    supports_model_config = False  # custom head, not catalog-built

    def _runner_class(self):
        return QRDQNRunner

    def _extra_runner_kwargs(self) -> Dict[str, Any]:
        return {"n_quantiles": self.algo_config.n_quantiles}

    def _make_q_learner(self, probe):
        cfg = self.algo_config
        return QRDQNLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, gamma=cfg.gamma, n_quantiles=cfg.n_quantiles,
            kappa=cfg.kappa, double_q=cfg.double_q, seed=cfg.seed)
