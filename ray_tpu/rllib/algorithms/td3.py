"""TD3 and DDPG: deterministic-policy continuous control.

Reference parity: rllib/algorithms/td3/td3.py (which extends
rllib/algorithms/ddpg/ddpg.py — TD3 = DDPG + twin clipped critics,
delayed policy updates, and target-policy smoothing; Fujimoto et al.
2018). DDPG here IS TD3 with policy_delay=1 and target smoothing off —
the same relationship the reference encodes in its config defaults.

TPU-first: the full update (critics + delayed actor + Polyak targets)
is one jitted JAX function; the delayed actor update is a lax.cond on
the step counter so the jit stays trace-stable.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class TD3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.env = "Pendulum-v1"
        self.tau = 0.005
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.expl_noise = 0.1           # rollout Gaussian noise (of half-range)
        self.target_noise = 0.2         # target-policy smoothing sigma
        self.target_noise_clip = 0.5
        self.policy_delay = 2           # actor updated every N critic steps
        self.buffer_capacity = 100_000
        self.random_warmup_steps = 500
        self.grad_steps_per_iter = 0    # 0 => one per sampled step
        self.train_batch_size = 256
        self.rollout_fragment_length = 64

    def training(self, *, tau=None, actor_lr=None, critic_lr=None,
                 expl_noise=None, target_noise=None, target_noise_clip=None,
                 policy_delay=None, buffer_capacity=None,
                 random_warmup_steps=None, grad_steps_per_iter=None,
                 **kw) -> "TD3Config":
        super().training(**kw)
        for name, v in (("tau", tau), ("actor_lr", actor_lr),
                        ("critic_lr", critic_lr), ("expl_noise", expl_noise),
                        ("target_noise", target_noise),
                        ("target_noise_clip", target_noise_clip),
                        ("policy_delay", policy_delay),
                        ("buffer_capacity", buffer_capacity),
                        ("random_warmup_steps", random_warmup_steps),
                        ("grad_steps_per_iter", grad_steps_per_iter)):
            if v is not None:
                setattr(self, name, v)
        return self


class DDPGConfig(TD3Config):
    """DDPG = TD3 minus its three additions (reference ddpg.py defaults)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.policy_delay = 1
        self.target_noise = 0.0
        self.target_noise_clip = 0.0


class TD3Learner:
    """Jitted TD3 update with a step-counter-gated actor update."""

    def __init__(self, obs_dim: int, action_dim: int, low: float,
                 high: float, *, hidden=(64, 64), actor_lr=1e-3,
                 critic_lr=1e-3, gamma=0.99, tau=0.005, target_noise=0.2,
                 target_noise_clip=0.5, policy_delay=2, seed=0):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.models import (det_actor_apply, det_actor_init,
                                          twin_q_apply, twin_q_init)
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.state = {
            "actor": det_actor_init(k1, obs_dim, action_dim,
                                    hidden=tuple(hidden)),
            "critic": twin_q_init(k2, obs_dim, action_dim,
                                  hidden=tuple(hidden)),
            "steps": jnp.int32(0),
        }
        self.state["target_actor"] = jax.tree_util.tree_map(
            lambda x: x, self.state["actor"])
        self.state["target_critic"] = jax.tree_util.tree_map(
            lambda x: x, self.state["critic"])
        self._opt_actor = optax.adam(actor_lr)
        self._opt_critic = optax.adam(critic_lr)
        self.opt_state = {
            "actor": self._opt_actor.init(self.state["actor"]),
            "critic": self._opt_critic.init(self.state["critic"]),
        }
        noise_scale = target_noise * (high - low) / 2.0
        noise_clip = target_noise_clip * (high - low) / 2.0

        def critic_loss(critic, state, batch, rng):
            a2 = det_actor_apply(state["target_actor"], batch[sb.NEXT_OBS],
                                 low, high)
            # target-policy smoothing: clipped noise on the target action
            eps = jnp.clip(noise_scale * jax.random.normal(rng, a2.shape),
                           -noise_clip, noise_clip)
            a2 = jnp.clip(a2 + eps, low, high)
            tq1, tq2 = twin_q_apply(state["target_critic"],
                                    batch[sb.NEXT_OBS], a2)
            target = batch[sb.REWARDS] + gamma * (
                1.0 - batch[sb.TERMINATEDS]) * jnp.minimum(tq1, tq2)
            target = jax.lax.stop_gradient(target)
            q1, q2 = twin_q_apply(critic, batch[sb.OBS], batch[sb.ACTIONS])
            return ((q1 - target) ** 2 + (q2 - target) ** 2).mean(), \
                0.5 * (q1.mean() + q2.mean())

        def actor_loss(actor, state, batch):
            a = det_actor_apply(actor, batch[sb.OBS], low, high)
            q1, _ = twin_q_apply(state["critic"], batch[sb.OBS], a)
            return -q1.mean()

        def update(state, opt_state, batch, rng):
            (c_loss, q_mean), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"], state, batch,
                                           rng)
            upd, opt_state["critic"] = self._opt_critic.update(
                c_grads, opt_state["critic"], state["critic"])
            state["critic"] = optax.apply_updates(state["critic"], upd)
            state["steps"] = state["steps"] + 1

            def do_actor(args):
                state, opt_state = args
                a_loss, a_grads = jax.value_and_grad(actor_loss)(
                    state["actor"], state, batch)
                upd, opt_actor = self._opt_actor.update(
                    a_grads, opt_state["actor"], state["actor"])
                state = dict(state,
                             actor=optax.apply_updates(state["actor"], upd))
                # Polyak sync both targets only on actor steps (TD3 paper)
                state["target_actor"] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    state["target_actor"], state["actor"])
                state["target_critic"] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    state["target_critic"], state["critic"])
                return state, dict(opt_state, actor=opt_actor), a_loss

            def skip_actor(args):
                state, opt_state = args
                return state, opt_state, jnp.float32(0.0)

            state, opt_state, a_loss = jax.lax.cond(
                state["steps"] % policy_delay == 0, do_actor, skip_actor,
                (state, opt_state))
            return state, opt_state, {
                "critic_loss": c_loss, "actor_loss": a_loss,
                "mean_q": q_mean,
            }

        self._jit_update = jax.jit(update)
        self._key = jax.random.PRNGKey(seed + 1)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp
        jb = {
            sb.OBS: jnp.asarray(batch[sb.OBS], jnp.float32),
            sb.ACTIONS: jnp.asarray(batch[sb.ACTIONS],
                                    jnp.float32).reshape(len(batch), -1),
            sb.REWARDS: jnp.asarray(batch[sb.REWARDS], jnp.float32),
            sb.NEXT_OBS: jnp.asarray(batch[sb.NEXT_OBS], jnp.float32),
            sb.TERMINATEDS: jnp.asarray(batch[sb.TERMINATEDS], jnp.float32),
        }
        self._key, sub = jax.random.split(self._key)
        self.state, self.opt_state, m = self._jit_update(
            self.state, self.opt_state, jb, sub)
        return {k: float(v) for k, v in m.items()}

    def get_actor_weights(self):
        return self.state["actor"]

    def get_weights(self):
        return self.state

    def set_weights(self, state):
        self.state = state


class TD3(Algorithm):
    config_class = TD3Config

    def setup(self, config: Dict[str, Any]):
        from ray_tpu.rllib.env import get_env_creator
        from ray_tpu.rllib.env_runner import ContinuousEnvRunner
        cfg = self.algo_config
        creator = get_env_creator(cfg.env)
        runner_cls = ray_tpu.remote(num_cpus=1)(ContinuousEnvRunner)
        self.env_runners = [
            runner_cls.remote(creator, cfg.env_config,
                              cfg.num_envs_per_env_runner,
                              seed=cfg.seed + 1000 * i, hidden=cfg.hidden,
                              policy="deterministic",
                              expl_noise=cfg.expl_noise,
                              obs_connectors=cfg.obs_connectors,
                              action_connectors=cfg.action_connectors)
            for i in range(cfg.num_env_runners)
        ]
        self._episode_rewards = []
        self._steps_sampled = 0
        self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.build_learner()

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = TD3Learner(
            probe.observation_dim, probe.action_dim, probe.action_low,
            probe.action_high, hidden=cfg.hidden, actor_lr=cfg.actor_lr,
            critic_lr=cfg.critic_lr, gamma=cfg.gamma, tau=cfg.tau,
            target_noise=cfg.target_noise,
            target_noise_clip=cfg.target_noise_clip,
            policy_delay=cfg.policy_delay, seed=cfg.seed)
        self.broadcast_weights(self.learner.get_actor_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        refs = [er.sample_transitions.remote(
            cfg.rollout_fragment_length, cfg.random_warmup_steps,
            self._steps_sampled) for er in self.env_runners]
        batch = concat_samples(ray_tpu.get(refs))
        self.buffer.add(batch)
        self._steps_sampled += len(batch)
        grad_steps = cfg.grad_steps_per_iter or len(batch)
        metrics: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.train_batch_size:
            for _ in range(grad_steps):
                m = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
            metrics.update(m)
        self.broadcast_weights(self.learner.get_actor_weights())
        metrics["num_env_steps_sampled"] = self._steps_sampled
        metrics["buffer_size"] = len(self.buffer)
        return metrics

    def save_checkpoint(self):
        return {"state": self.learner.get_weights(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["state"])
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_actor_weights())


class DDPG(TD3):
    config_class = DDPGConfig
