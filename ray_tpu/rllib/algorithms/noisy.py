"""NoisyNet-DQN: learned parametric exploration.

Reference parity: the reference's DQN exposes `noisy: True` in its model
config (rllib/algorithms/dqn, NoisyLayer in rllib/models) — Fortunato et
al. 2018 factorized Gaussian noisy linear layers replace epsilon-greedy:
every weight is mu + sigma * (f(eps_in) f(eps_out)^T) with f(x) =
sign(x)sqrt(|x|); exploration pressure comes from the learned sigmas and
decays only where the data says it should. Epsilon is forced to zero.

The noise is resampled OUTSIDE jit (a PRNG key per forward) so one
compiled program serves every step.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.dqn import (DQN, DQNConfig, NSTEP_GAMMAS)
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.sample_batch import SampleBatch


class NoisyDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or NoisyDQN)
        self.sigma0 = 0.5          # initial sigma scale (paper default)
        # Exploration is the noise itself.
        self.epsilon_start = 0.0
        self.epsilon_end = 0.0

    def training(self, *, sigma0=None, **kw) -> "NoisyDQNConfig":
        super().training(**kw)
        if sigma0 is not None:
            self.sigma0 = sigma0
        return self


def noisy_net_init(seed: int, sizes, sigma0: float = 0.5):
    """Stack of factorized-noise linear layers: each layer holds
    (mu_w, mu_b, sig_w, sig_b); sigma init = sigma0/sqrt(fan_in)."""
    import jax
    import jax.numpy as jnp
    rng = jax.random.PRNGKey(seed)
    layers = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (fi, fo) in zip(keys, zip(sizes[:-1], sizes[1:])):
        bound = 1.0 / np.sqrt(fi)
        k1, k2 = jax.random.split(k)
        layers.append({
            "mu_w": jax.random.uniform(k1, (fi, fo), jnp.float32,
                                       -bound, bound),
            "mu_b": jax.random.uniform(k2, (fo,), jnp.float32,
                                       -bound, bound),
            "sig_w": jnp.full((fi, fo), sigma0 / np.sqrt(fi), jnp.float32),
            "sig_b": jnp.full((fo,), sigma0 / np.sqrt(fi), jnp.float32),
        })
    return layers


def noisy_net_apply(layers, x, key):
    """Forward with factorized noise drawn from `key`; key=None gives the
    deterministic mu-only net (evaluation mode)."""
    import jax
    import jax.numpy as jnp

    def f(e):
        return jnp.sign(e) * jnp.sqrt(jnp.abs(e))

    for i, layer in enumerate(layers):
        if key is None:
            w, b = layer["mu_w"], layer["mu_b"]
        else:
            key, k1, k2 = jax.random.split(key, 3)
            e_in = f(jax.random.normal(k1, (layer["mu_w"].shape[0],)))
            e_out = f(jax.random.normal(k2, (layer["mu_w"].shape[1],)))
            w = layer["mu_w"] + layer["sig_w"] * jnp.outer(e_in, e_out)
            b = layer["mu_b"] + layer["sig_b"] * e_out
        x = x @ w + b
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


class NoisyDQNRunner(EnvRunner):
    """Greedy over the noisy Q values — a fresh noise draw per forward is
    the exploration policy (no epsilon)."""

    def __init__(self, *args, sigma0=0.5, **kw):
        self._sigma0 = sigma0
        super().__init__(*args, **kw)

    def _build_policy(self, seed, hidden, model):
        import jax
        e0 = self._envs[0]
        self._params = {"q": noisy_net_init(
            seed, [e0.observation_dim, *hidden, e0.num_actions],
            self._sigma0)}
        self._noise_key = jax.random.PRNGKey(seed + 77)
        jit_q = jax.jit(lambda p, o, k: noisy_net_apply(p["q"], o, k))

        def forward(p, obs):
            self._noise_key, sub = jax.random.split(self._noise_key)
            q = jit_q(p, obs, sub)
            return q, q.max(-1)

        # Plain callable: sample_transitions only calls it.
        self._jit_forward = forward


class NoisyDQNLearner:
    def __init__(self, obs_dim: int, num_actions: int, *, hidden=(64, 64),
                 lr=5e-4, gamma=0.99, double_q=True, sigma0=0.5, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._gamma = gamma
        self.params = {"q": noisy_net_init(
            seed, [obs_dim, *hidden, num_actions], sigma0)}
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.opt_state = self._optimizer.init(self.params)
        self._key = jax.random.PRNGKey(seed + 13)

        def loss_fn(params, target_params, batch, weights, keys):
            # Independent noise draws for online, selection, and target
            # nets (the paper's independent-noise TD estimate).
            q = noisy_net_apply(params["q"], batch[sb.OBS], keys[0])
            n = q.shape[0]
            q_taken = q[jnp.arange(n), batch[sb.ACTIONS]]
            q_next_t = noisy_net_apply(target_params["q"],
                                       batch[sb.NEXT_OBS], keys[1])
            if double_q:
                q_next_sel = noisy_net_apply(params["q"],
                                             batch[sb.NEXT_OBS], keys[2])
                a_next = jnp.argmax(q_next_sel, -1)
                v_next = q_next_t[jnp.arange(n), a_next]
            else:
                v_next = q_next_t.max(-1)
            not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
            target = (batch[sb.REWARDS]
                      + batch[NSTEP_GAMMAS] * not_done * v_next)
            td = q_taken - jax.lax.stop_gradient(target)
            return (weights * td * td).mean(), jnp.abs(td)

        def update(params, target_params, opt_state, batch, weights,
                   keys):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch,
                                       weights, keys)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
               sb.TERMINATEDS)}
        jb[NSTEP_GAMMAS] = (jnp.asarray(batch[NSTEP_GAMMAS])
                            if NSTEP_GAMMAS in batch
                            else jnp.full(len(batch), self._gamma,
                                          jnp.float32))
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self._key, *keys = jax.random.split(self._key, 4)
        self.params, self.opt_state, loss, td = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights,
            tuple(keys))
        return {"td_error": np.asarray(td), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class NoisyDQN(DQN):
    config_class = NoisyDQNConfig
    supports_model_config = False  # custom head, not catalog-built

    def _runner_class(self):
        return NoisyDQNRunner

    def _extra_runner_kwargs(self) -> Dict[str, Any]:
        return {"sigma0": self.algo_config.sigma0}

    def _make_q_learner(self, probe):
        cfg = self.algo_config
        return NoisyDQNLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, gamma=cfg.gamma, double_q=cfg.double_q,
            sigma0=cfg.sigma0, seed=cfg.seed)
