"""DQN: replay-buffer Q-learning with a target network and double-Q
bootstrapping.

Reference parity: rllib/algorithms/dqn/dqn.py (training_step: sample ->
store -> replay -> TD update -> target sync) with optional prioritized
replay (rllib/utils/replay_buffers/prioritized_replay_buffer.py). The
policy MLP's action head doubles as the Q head (policy_value_init "pi"
network); exploration is epsilon-greedy with linear decay.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import mlp_apply, policy_value_init
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.rollout_fragment_length = 32
        self.replay_buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_network_update_freq = 500   # in sampled env steps
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.double_q = True
        self.prioritized_replay = False
        self.train_batch_size = 64
        self.updates_per_step = 4

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, epsilon_start=None,
                 epsilon_end=None, epsilon_decay_steps=None, double_q=None,
                 prioritized_replay=None, updates_per_step=None,
                 **kw) -> "DQNConfig":
        super().training(**kw)
        for name, val in (("replay_buffer_capacity", replay_buffer_capacity),
                          ("learning_starts", learning_starts),
                          ("target_network_update_freq",
                           target_network_update_freq),
                          ("epsilon_start", epsilon_start),
                          ("epsilon_end", epsilon_end),
                          ("epsilon_decay_steps", epsilon_decay_steps),
                          ("double_q", double_q),
                          ("prioritized_replay", prioritized_replay),
                          ("updates_per_step", updates_per_step)):
            if val is not None:
                setattr(self, name, val)
        return self


class DQNLearner:
    def __init__(self, obs_dim: int, num_actions: int, *, hidden=(64, 64),
                 lr=5e-4, gamma=0.99, double_q=True, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self.params = policy_value_init(jax.random.PRNGKey(seed), obs_dim,
                                        num_actions, hidden=tuple(hidden))
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt_state = self._optimizer.init(self.params)

        def q_values(params, obs):
            # Q head = the "pi" MLP without the small-logits scaling.
            return mlp_apply(params["pi"], obs)

        def loss_fn(params, target_params, batch, weights):
            q = q_values(params, batch[sb.OBS])
            n = q.shape[0]
            q_taken = q[jnp.arange(n), batch[sb.ACTIONS]]
            q_next_target = q_values(target_params, batch[sb.NEXT_OBS])
            if double_q:
                # Action chosen by the ONLINE net, valued by the target net.
                a_next = jnp.argmax(q_values(params, batch[sb.NEXT_OBS]), -1)
                v_next = q_next_target[jnp.arange(n), a_next]
            else:
                v_next = q_next_target.max(-1)
            not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
            target = batch[sb.REWARDS] + gamma * not_done * v_next
            td = q_taken - jax.lax.stop_gradient(target)
            loss = (weights * td * td).mean()
            return loss, jnp.abs(td)

        def update(params, target_params, opt_state, batch, weights):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch, weights)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS, sb.TERMINATEDS)}
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self.params, self.opt_state, loss, td = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights)
        return {"td_error": np.asarray(td), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class DQN(Algorithm):
    config_class = DQNConfig

    def _make_q_learner(self, probe):
        """Q-learner factory; the distributional variant (C51) overrides
        just this instead of copying build_learner."""
        cfg = self.algo_config
        return DQNLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, gamma=cfg.gamma, double_q=cfg.double_q,
            seed=cfg.seed)

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = self._make_q_learner(probe)
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        self.replay = buf_cls(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._steps_sampled = 0
        self._last_target_sync = 0
        self.broadcast_weights(self.learner.get_weights())

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end
                                           - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        batch = concat_samples(ray_tpu.get(
            [er.sample_transitions.remote(cfg.rollout_fragment_length, eps)
             for er in self.env_runners]))
        self.replay.add(batch)
        self._steps_sampled += len(batch)
        metrics: Dict[str, Any] = {"epsilon": eps,
                                   "replay_size": len(self.replay),
                                   "num_env_steps_sampled": len(batch)}
        if len(self.replay) >= cfg.learning_starts:
            losses = []
            for _ in range(cfg.updates_per_step):
                replayed = self.replay.sample(cfg.train_batch_size)
                m = self.learner.update(replayed)
                if cfg.prioritized_replay and "batch_indexes" in replayed:
                    self.replay.update_priorities(
                        replayed["batch_indexes"], m["td_error"] + 1e-6)
                losses.append(m["loss"])
            metrics["loss"] = float(np.mean(losses))
            self.broadcast_weights(self.learner.get_weights())
        if (self._steps_sampled - self._last_target_sync
                >= cfg.target_network_update_freq):
            self.learner.sync_target()
            self._last_target_sync = self._steps_sampled
        return metrics

    def save_checkpoint(self):
        return {"params": self.learner.get_weights(),
                "target": self.learner.target_params,
                "steps": self._steps_sampled,
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["params"])
        self.learner.target_params = ckpt["target"]
        self._steps_sampled = ckpt.get("steps", 0)
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_weights())
