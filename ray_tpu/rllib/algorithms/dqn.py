"""DQN: replay-buffer Q-learning with a target network and double-Q
bootstrapping.

Reference parity: rllib/algorithms/dqn/dqn.py (training_step: sample ->
store -> replay -> TD update -> target sync) with optional prioritized
replay (rllib/utils/replay_buffers/prioritized_replay_buffer.py). The
policy MLP's action head doubles as the Q head (policy_value_init "pi"
network); exploration is epsilon-greedy with linear decay.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.catalog import obs_shape_of
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.models import mlp_apply, policy_value_init
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.rollout_fragment_length = 32
        self.n_step = 1
        self.replay_buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_network_update_freq = 500   # in sampled env steps
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.double_q = True
        self.dueling = False
        self.prioritized_replay = False
        self.train_batch_size = 64
        self.updates_per_step = 4

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, epsilon_start=None,
                 epsilon_end=None, epsilon_decay_steps=None, double_q=None,
                 prioritized_replay=None, updates_per_step=None,
                 n_step=None, dueling=None, **kw) -> "DQNConfig":
        super().training(**kw)
        for name, val in (("n_step", n_step),
                          ("dueling", dueling),
                          ("replay_buffer_capacity", replay_buffer_capacity),
                          ("learning_starts", learning_starts),
                          ("target_network_update_freq",
                           target_network_update_freq),
                          ("epsilon_start", epsilon_start),
                          ("epsilon_end", epsilon_end),
                          ("epsilon_decay_steps", epsilon_decay_steps),
                          ("double_q", double_q),
                          ("prioritized_replay", prioritized_replay),
                          ("updates_per_step", updates_per_step)):
            if val is not None:
                setattr(self, name, val)
        return self


NSTEP_GAMMAS = "nstep_gammas"


def nstep_transform(batch: SampleBatch, n: int, gamma: float,
                    num_envs: int) -> SampleBatch:
    """Collapse 1-step transitions into n-step ones (reference:
    rllib/utils/replay_buffers/utils.py n-step logic).

    sample_transitions interleaves env copies per timestep
    ([t0e0, t0e1, t1e0, ...]); each env's stream is de-interleaved,
    rewards are accumulated sum_{k<m} gamma^k r_{t+k} with the window
    cut at terminations and the fragment tail, next_obs comes from the
    window's last step, and a per-sample bootstrap discount gamma^m is
    recorded (windows truncated by episode end or fragment end have
    m < n, so a scalar gamma^n would be wrong).
    """
    if n <= 1:
        return batch
    size = len(batch)
    t_steps = size // num_envs
    out = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
                           sb.TERMINATEDS, NSTEP_GAMMAS)}
    trunc_all = batch.get(sb.TRUNCATEDS,
                          np.zeros(size, dtype=bool))
    for e in range(num_envs):
        idx = np.arange(t_steps) * num_envs + e
        rew = batch[sb.REWARDS][idx]
        term = batch[sb.TERMINATEDS][idx]
        trunc = trunc_all[idx]
        for t in range(t_steps):
            r_acc, m = 0.0, 0
            for k in range(n):
                if t + k >= t_steps:
                    break
                r_acc += (gamma ** k) * float(rew[t + k])
                m = k + 1
                # The env resets after term OR trunc: the window must not
                # bridge into the next episode's stream.
                if term[t + k] or trunc[t + k]:
                    break
            last = idx[t + m - 1]
            out[sb.OBS].append(batch[sb.OBS][idx[t]])
            out[sb.ACTIONS].append(batch[sb.ACTIONS][idx[t]])
            out[sb.REWARDS].append(r_acc)
            out[sb.NEXT_OBS].append(batch[sb.NEXT_OBS][last])
            out[sb.TERMINATEDS].append(batch[sb.TERMINATEDS][last])
            out[NSTEP_GAMMAS].append(gamma ** m)
    return SampleBatch({k: np.asarray(v) for k, v in out.items()})


class DQNLearner:
    def __init__(self, obs_dim: int, num_actions: int, *, hidden=(64, 64),
                 lr=5e-4, gamma=0.99, double_q=True, dueling=False,
                 obs_shape=None, model=None, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._gamma = gamma
        if model is not None:
            # Catalog Q-net (CNN torso for image observations).
            from ray_tpu.rllib.catalog import (ModelConfig,
                                               catalog_q_apply,
                                               catalog_q_init)
            mcfg = ModelConfig.from_dict(model)
            shape = tuple(obs_shape) if obs_shape else (obs_dim,)
            self.params = catalog_q_init(jax.random.PRNGKey(seed), shape,
                                         num_actions, mcfg)

            def q_values(params, obs):
                return catalog_q_apply(params, obs, mcfg)
        else:
            self.params = policy_value_init(
                jax.random.PRNGKey(seed), obs_dim, num_actions,
                hidden=tuple(hidden))

            def q_values(params, obs):
                # Q head = the "pi" MLP without the small-logits scaling.
                # Dueling (Wang et al. 2016; reference model config
                # dueling=True): the "vf" stream is the state value and
                # "pi" becomes the advantage stream, combined with the
                # mean-advantage identifiability constraint.
                adv = mlp_apply(params["pi"], obs)
                if dueling:
                    v = mlp_apply(params["vf"], obs)
                    return v + adv - adv.mean(-1, keepdims=True)
                return adv
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt_state = self._optimizer.init(self.params)

        def loss_fn(params, target_params, batch, weights):
            q = q_values(params, batch[sb.OBS])
            n = q.shape[0]
            q_taken = q[jnp.arange(n), batch[sb.ACTIONS]]
            q_next_target = q_values(target_params, batch[sb.NEXT_OBS])
            if double_q:
                # Action chosen by the ONLINE net, valued by the target net.
                a_next = jnp.argmax(q_values(params, batch[sb.NEXT_OBS]), -1)
                v_next = q_next_target[jnp.arange(n), a_next]
            else:
                v_next = q_next_target.max(-1)
            not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
            # Per-sample bootstrap discount: gamma for 1-step, gamma^m
            # for n-step windows (m < n at episode/fragment cuts).
            target = (batch[sb.REWARDS]
                      + batch[NSTEP_GAMMAS] * not_done * v_next)
            td = q_taken - jax.lax.stop_gradient(target)
            loss = (weights * td * td).mean()
            return loss, jnp.abs(td)

        def update(params, target_params, opt_state, batch, weights):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch, weights)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS, sb.TERMINATEDS)}
        jb[NSTEP_GAMMAS] = (jnp.asarray(batch[NSTEP_GAMMAS])
                            if NSTEP_GAMMAS in batch
                            else jnp.full(len(batch), self._gamma,
                                          jnp.float32))
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self.params, self.opt_state, loss, td = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights)
        return {"td_error": np.asarray(td), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class CatalogQRunner(EnvRunner):
    """EnvRunner whose greedy scores come from the catalog Q-net (CNN
    torso for image observations) — matches DQNLearner's model path."""

    def _build_policy(self, seed, hidden, model):
        import jax
        from ray_tpu.rllib.catalog import (ModelConfig, catalog_q_apply,
                                           catalog_q_init, obs_shape_of)
        e0 = self._envs[0]
        mcfg = ModelConfig.from_dict(model)
        self._params = catalog_q_init(jax.random.PRNGKey(seed),
                                      obs_shape_of(e0), e0.num_actions,
                                      mcfg)

        def fwd(p, obs):
            q = catalog_q_apply(p, obs, mcfg)
            return q, q.max(-1)

        self._jit_forward = jax.jit(fwd)


class DuelingDQNRunner(EnvRunner):
    """EnvRunner whose greedy scores combine the value + advantage
    streams exactly as the dueling learner's q_values does."""

    def _build_policy(self, seed, hidden, model):
        import jax
        e0 = self._envs[0]
        self._params = policy_value_init(
            jax.random.PRNGKey(seed), e0.observation_dim,
            e0.num_actions, hidden=tuple(hidden))

        def fwd(p, obs):
            adv = mlp_apply(p["pi"], obs)
            q = mlp_apply(p["vf"], obs) + adv \
                - adv.mean(-1, keepdims=True)
            return q, q.max(-1)

        self._jit_forward = jax.jit(fwd)


class DQN(Algorithm):
    config_class = DQNConfig
    # Catalog model configs (CNN Q-nets) supported by DQN/APEX; the
    # distributional/noisy variants build their own heads and opt out.
    supports_model_config = True

    def _validate_config(self):
        super()._validate_config()
        cfg = self.algo_config
        # Catalog-combo checks only apply where the catalog is in play
        # (opted-out variants route model=None and keep the legacy net).
        if cfg.model is not None and self.supports_model_config:
            if cfg.dueling:
                raise ValueError("dueling=True cannot combine with a "
                                 "catalog model config")
            from ray_tpu.rllib.catalog import ModelConfig
            if ModelConfig.from_dict(cfg.model).use_lstm:
                raise ValueError("use_lstm is not supported for "
                                 "value-based Q networks (R2D2 "
                                 "territory)")

    def _runner_class(self):
        if self.algo_config.model is not None:
            return CatalogQRunner
        return (DuelingDQNRunner if self.algo_config.dueling
                else EnvRunner)

    def _make_q_learner(self, probe):
        """Q-learner factory; the distributional variant (C51) overrides
        just this instead of copying build_learner."""
        cfg = self.algo_config
        return DQNLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, gamma=cfg.gamma, double_q=cfg.double_q,
            dueling=cfg.dueling, seed=cfg.seed,
            obs_shape=obs_shape_of(probe), model=cfg.model)

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = self._make_q_learner(probe)
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        self.replay = buf_cls(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._steps_sampled = 0
        self._last_target_sync = 0
        self.broadcast_weights(self.learner.get_weights())

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._steps_sampled / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end
                                           - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        eps = self._epsilon()
        batches = ray_tpu.get(
            [er.sample_transitions.remote(cfg.rollout_fragment_length, eps)
             for er in self.env_runners])
        if cfg.n_step > 1:
            # Per-runner (each runner's batch has its own env interleave).
            batches = [nstep_transform(b, cfg.n_step, cfg.gamma,
                                       cfg.num_envs_per_env_runner)
                       for b in batches]
        batch = concat_samples(batches)
        self.replay.add(batch)
        self._steps_sampled += len(batch)
        metrics: Dict[str, Any] = {"epsilon": eps,
                                   "replay_size": len(self.replay),
                                   "num_env_steps_sampled": len(batch)}
        if len(self.replay) >= cfg.learning_starts:
            losses = []
            for _ in range(cfg.updates_per_step):
                replayed = self.replay.sample(cfg.train_batch_size)
                m = self.learner.update(replayed)
                if cfg.prioritized_replay and "batch_indexes" in replayed:
                    self.replay.update_priorities(
                        replayed["batch_indexes"], m["td_error"] + 1e-6)
                losses.append(m["loss"])
            metrics["loss"] = float(np.mean(losses))
            self.broadcast_weights(self.learner.get_weights())
        if (self._steps_sampled - self._last_target_sync
                >= cfg.target_network_update_freq):
            self.learner.sync_target()
            self._last_target_sync = self._steps_sampled
        return metrics

    def save_checkpoint(self):
        return {"params": self.learner.get_weights(),
                "target": self.learner.target_params,
                "steps": self._steps_sampled,
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["params"])
        self.learner.target_params = ckpt["target"]
        self._steps_sampled = ckpt.get("steps", 0)
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_weights())
