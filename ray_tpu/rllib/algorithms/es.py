"""ES: OpenAI-style Evolution Strategies (Salimans et al. 2017).

Reference parity: rllib/algorithms/es/es.py — derivative-free policy
search: each iteration samples antithetic parameter perturbations, scores
them with full greedy episodes on the EnvRunner fleet, and ascends the
centered-rank-weighted noise direction. Noise never ships: runners
rebuild each perturbation from its integer seed (the shared-noise-table
trick). ARS (Mania et al. 2018) rides the same machinery with top-k
direction selection and reward-std scaling (rllib/algorithms/ars).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.episodes_per_perturbation = 1
        self.noise_stdev = 0.05
        self.step_size = 0.02
        self.num_perturbations = 16     # antithetic pairs per iteration
        self.max_episode_steps = 500
        self.l2_coeff = 0.005
        self.num_epochs = 1

    def training(self, *, noise_stdev=None, step_size=None,
                 num_perturbations=None, episodes_per_perturbation=None,
                 max_episode_steps=None, l2_coeff=None,
                 **kw) -> "ESConfig":
        super().training(**kw)
        for name, v in (("noise_stdev", noise_stdev),
                        ("step_size", step_size),
                        ("num_perturbations", num_perturbations),
                        ("episodes_per_perturbation",
                         episodes_per_perturbation),
                        ("max_episode_steps", max_episode_steps),
                        ("l2_coeff", l2_coeff)):
            if v is not None:
                setattr(self, name, v)
        return self


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: returns -> centered ranks in [-0.5, 0.5]
    (reference: es/utils.py compute_centered_ranks)."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / max(1, len(x) - 1) - 0.5


class ES(Algorithm):
    config_class = ESConfig

    def build_learner(self):
        cfg = self.algo_config
        # copy: ray_tpu.get of a numpy array is a READ-ONLY zero-copy
        # view into plasma; theta is updated in place every iteration.
        self.theta = np.array(ray_tpu.get(
            self.env_runners[0].get_flat_params.remote(), timeout=120),
            np.float32, copy=True)
        self._seed_counter = cfg.seed * 100003 + 1
        # Adam-style moments keep the step scale stable across iterations
        # (the reference's Adam optimizer over the flat theta).
        self._m = np.zeros_like(self.theta)
        self._v = np.zeros_like(self.theta)
        self._t = 0

    def _next_seeds(self, n: int):
        out = list(range(self._seed_counter, self._seed_counter + n))
        self._seed_counter += n
        return out

    def _update_theta(self, grad: np.ndarray):
        cfg = self.algo_config
        self._t += 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        self._m = b1 * self._m + (1 - b1) * grad
        self._v = b2 * self._v + (1 - b2) * grad * grad
        mhat = self._m / (1 - b1 ** self._t)
        vhat = self._v / (1 - b2 ** self._t)
        self.theta += cfg.step_size * mhat / (np.sqrt(vhat) + eps)

    def _perturbation_returns(self, seeds):
        """Fan seeds across runners; -> (r_pos[n], r_neg[n])."""
        cfg = self.algo_config
        chunks = np.array_split(np.asarray(seeds), len(self.env_runners))
        refs = [
            runner.evaluate_perturbations.remote(
                self.theta, [int(s) for s in chunk], cfg.noise_stdev,
                cfg.episodes_per_perturbation, cfg.max_episode_steps)
            for runner, chunk in zip(self.env_runners, chunks)
            if len(chunk)
        ]
        pairs = [p for chunk in ray_tpu.get(refs, timeout=600)
                 for p in chunk]
        r = np.asarray(pairs, np.float32)
        return r[:, 0], r[:, 1]

    def _gradient(self, seeds, r_pos, r_neg) -> np.ndarray:
        cfg = self.algo_config
        weights = _centered_ranks(np.concatenate([r_pos, r_neg]))
        w = weights[:len(seeds)] - weights[len(seeds):]
        grad = np.zeros_like(self.theta)
        for s, wi in zip(seeds, w):
            eps = np.random.RandomState(s).standard_normal(
                self.theta.shape).astype(np.float32)
            grad += wi * eps
        grad /= (2 * len(seeds) * cfg.noise_stdev)
        return grad - cfg.l2_coeff * self.theta

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        seeds = self._next_seeds(cfg.num_perturbations)
        r_pos, r_neg = self._perturbation_returns(seeds)
        self._update_theta(self._gradient(seeds, r_pos, r_neg))
        # Score the updated policy: a zero-sigma "perturbation" evaluates
        # exactly theta (the runner unravels the flat vector itself).
        eval_ref = self.env_runners[0].evaluate_perturbations.remote(
            self.theta, [0], 0.0, 1, cfg.max_episode_steps)
        cur = float(ray_tpu.get(eval_ref, timeout=600)[0][0])
        return {
            "episode_reward_mean": cur,
            "perturbation_reward_mean": float(
                np.mean(np.concatenate([r_pos, r_neg]))),
            "perturbation_reward_max": float(
                np.max(np.concatenate([r_pos, r_neg]))),
            "theta_norm": float(np.linalg.norm(self.theta)),
        }

    def save_checkpoint(self):
        return {"theta": self.theta.copy(), "t": self._t,
                "m": self._m.copy(), "v": self._v.copy(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.theta = np.asarray(ckpt["theta"], np.float32)
        self._t = ckpt.get("t", 0)
        self._m = np.asarray(ckpt.get("m", np.zeros_like(self.theta)))
        self._v = np.asarray(ckpt.get("v", np.zeros_like(self.theta)))
        self._iteration = ckpt.get("iteration", 0)


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.top_directions = 8      # use best k of num_perturbations
        self.noise_stdev = 0.05
        self.step_size = 0.05

    def training(self, *, top_directions=None, **kw) -> "ARSConfig":
        super().training(**kw)
        if top_directions is not None:
            self.top_directions = top_directions
        return self


class ARS(ES):
    """Augmented Random Search (reference: rllib/algorithms/ars): keep
    only the top-k directions by max(r_pos, r_neg) and scale the step by
    the std of the surviving returns."""

    config_class = ARSConfig

    def _gradient(self, seeds, r_pos, r_neg) -> np.ndarray:
        cfg = self.algo_config
        k = min(cfg.top_directions, len(seeds))
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        kept = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = float(kept.std()) or 1.0
        grad = np.zeros_like(self.theta)
        for i in order:
            eps = np.random.RandomState(seeds[i]).standard_normal(
                self.theta.shape).astype(np.float32)
            grad += (r_pos[i] - r_neg[i]) * eps
        return grad / (k * sigma_r)
