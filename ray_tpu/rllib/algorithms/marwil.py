"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning.

Reference parity: rllib/algorithms/marwil/marwil.py (Wang et al. 2018):
offline imitation where each action's log-likelihood is weighted by
exp(beta * advantage), with a learned value baseline — beta=0 degrades to
plain BC (the reference's BC literally subclasses MARWIL with beta=0).

Returns-to-go are computed per stored episode fragment at load time from
the REWARDS/TERMINATEDS columns.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import policy_value_apply, policy_value_init
from ray_tpu.rllib.offline import JsonReader
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.input_path = ""
        self.beta = 1.0                 # advantage exponent; 0 => BC
        self.vf_coeff = 1.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-2
        self.train_batch_size = 256
        self.num_env_runners = 0

    def offline_data(self, *, input_path=None) -> "MARWILConfig":
        if input_path is not None:
            self.input_path = input_path
        return self

    def training(self, *, beta=None, vf_coeff=None, **kw) -> "MARWILConfig":
        super().training(**kw)
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        return self


def _returns_to_go(batch: SampleBatch, gamma: float) -> np.ndarray:
    """Discounted returns within one stored fragment; episode boundaries
    from TERMINATEDS (reference: marwil postprocesses with
    compute_advantages over complete episodes)."""
    r = np.asarray(batch[sb.REWARDS], np.float32)
    done = np.asarray(batch.get(sb.TERMINATEDS, np.zeros_like(r)),
                      np.float32)
    out = np.zeros_like(r)
    acc = 0.0
    for i in range(len(r) - 1, -1, -1):
        acc = r[i] + gamma * acc * (1.0 - done[i])
        out[i] = acc
    return out


class MARWIL(Algorithm):
    config_class = MARWILConfig

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if not cfg.input_path:
            raise ValueError(
                "MARWIL requires config.offline_data(input_path=...)")
        self.env_runners = []
        self._episode_rewards = []
        reader = JsonReader(cfg.input_path, seed=cfg.seed)
        frags = []
        for frag in reader.iter_batches():
            frag["returns"] = _returns_to_go(frag, cfg.gamma)
            frags.append(frag)
        self.data = concat_samples(frags)
        self._rng = np.random.RandomState(cfg.seed)
        self.build_learner()

    def build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.params = policy_value_init(
            jax.random.PRNGKey(cfg.seed), probe.observation_dim,
            probe.num_actions, hidden=cfg.hidden)
        self._optimizer = optax.adam(cfg.lr)
        self.opt_state = self._optimizer.init(self.params)
        # running normalizer for squared advantages (reference:
        # marwil_torch_policy ma_adv_norm) kept as a jax scalar carry.
        self._adv_norm = jnp.float32(100.0)
        beta, vf_coeff = cfg.beta, cfg.vf_coeff
        rate = cfg.moving_average_sqd_adv_norm_update_rate

        def loss_fn(params, adv_norm, obs, actions, returns):
            logits, values = policy_value_apply(params, obs)
            adv = returns - values
            new_norm = adv_norm + rate * (
                jax.lax.stop_gradient((adv ** 2).mean()) - adv_norm)
            w = jnp.exp(beta * jax.lax.stop_gradient(
                adv / jnp.sqrt(new_norm + 1e-8)))
            w = jnp.minimum(w, 20.0)  # clip exploding weights
            logp = jax.nn.log_softmax(logits)
            n = logits.shape[0]
            policy_loss = -(w * logp[jnp.arange(n), actions]).mean()
            vf_loss = (adv ** 2).mean()
            return policy_loss + vf_coeff * vf_loss, (
                new_norm, policy_loss, vf_loss)

        def update(params, opt_state, adv_norm, obs, actions, returns):
            (loss, (new_norm, p_loss, v_loss)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, adv_norm, obs, actions,
                                       returns)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return (optax.apply_updates(params, updates), opt_state,
                    new_norm, loss, p_loss, v_loss)

        self._jit_update = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        cfg = self.algo_config
        n = len(self.data)
        idx = self._rng.randint(0, n, size=min(cfg.train_batch_size, n))
        obs = jnp.asarray(self.data[sb.OBS][idx])
        actions = jnp.asarray(self.data[sb.ACTIONS][idx])
        returns = jnp.asarray(self.data["returns"][idx])
        (self.params, self.opt_state, self._adv_norm, loss, p_loss,
         v_loss) = self._jit_update(self.params, self.opt_state,
                                    self._adv_norm, obs, actions, returns)
        return {"loss": float(loss), "policy_loss": float(p_loss),
                "vf_loss": float(v_loss),
                "num_samples_trained": int(len(idx)),
                "episode_reward_mean": float("nan")}

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        import jax
        cfg = self.algo_config
        env = make_env(cfg.env, cfg.env_config)
        fwd = jax.jit(policy_value_apply)
        rewards = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=cfg.seed + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = fwd(self.params, obs[None, :])
                a = int(np.argmax(np.asarray(logits)[0]))
                obs, r, term, trunc, _ = env.step(a)
                total += r
                done = term or trunc
            rewards.append(total)
        return {"evaluation_reward_mean": float(np.mean(rewards))}

    def save_checkpoint(self):
        return {"params": self.params, "adv_norm": self._adv_norm,
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.params = ckpt["params"]
        self._adv_norm = ckpt.get("adv_norm", self._adv_norm)
        self._iteration = ckpt.get("iteration", 0)

    def cleanup(self):
        pass
