"""C51: categorical distributional DQN.

Reference parity: rllib/algorithms/dqn with num_atoms>1 (the C51 head of
the reference's distributional Q-model, rllib/models catalog
num_atoms/v_min/v_max). The Q network emits a categorical distribution
over `n_atoms` fixed support atoms per action; the TD update projects the
Bellman-shifted target distribution back onto the support and minimizes
cross-entropy (Bellemare et al. 2017). The whole projection is vectorized
inside one jitted update — no per-sample Python.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig, NSTEP_GAMMAS
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.models import mlp_apply, policy_value_init
from ray_tpu.rllib.sample_batch import SampleBatch


class C51Config(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or C51)
        self.n_atoms = 51
        self.v_min = -10.0
        self.v_max = 10.0

    def training(self, *, n_atoms=None, v_min=None, v_max=None,
                 **kw) -> "C51Config":
        super().training(**kw)
        for name, val in (("n_atoms", n_atoms), ("v_min", v_min),
                          ("v_max", v_max)):
            if val is not None:
                setattr(self, name, val)
        return self


def _dist_init(seed, obs_dim, num_actions, n_atoms, hidden):
    import jax
    return policy_value_init(jax.random.PRNGKey(seed), obs_dim,
                             num_actions * n_atoms, hidden=tuple(hidden))


class C51Runner(EnvRunner):
    """EnvRunner whose greedy scores are EXPECTED Q values under the
    categorical head (argmax over raw A*N logits would be meaningless)."""

    def __init__(self, *args, n_atoms=51, v_min=-10.0, v_max=10.0, **kw):
        # Set before super().__init__: the base ctor calls _build_policy.
        self._n_atoms = n_atoms
        self._v_min, self._v_max = v_min, v_max
        super().__init__(*args, **kw)

    def _build_policy(self, seed, hidden, model):
        import jax
        import jax.numpy as jnp
        e0 = self._envs[0]
        n_act = e0.num_actions
        n_atoms = self._n_atoms
        z = jnp.linspace(self._v_min, self._v_max, n_atoms)
        self._params = _dist_init(seed, e0.observation_dim, n_act,
                                  n_atoms, hidden)

        def fwd(p, obs):
            logits = mlp_apply(p["pi"], obs)
            d = jax.nn.softmax(
                logits.reshape(obs.shape[0], n_act, n_atoms), -1)
            q = (d * z).sum(-1)
            return q, q.max(-1)

        self._jit_forward = jax.jit(fwd)


class C51Learner:
    def __init__(self, obs_dim: int, num_actions: int, *, hidden=(64, 64),
                 lr=5e-4, gamma=0.99, n_atoms=51, v_min=-10.0, v_max=10.0,
                 double_q=True, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self._optimizer = optax.adam(lr)
        self._gamma = gamma
        self.params = _dist_init(seed, obs_dim, num_actions, n_atoms,
                                 hidden)
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.opt_state = self._optimizer.init(self.params)
        z = jnp.linspace(v_min, v_max, n_atoms)
        dz = (v_max - v_min) / (n_atoms - 1)

        def dist_logits(params, obs):
            out = mlp_apply(params["pi"], obs)
            return out.reshape(obs.shape[0], num_actions, n_atoms)

        def loss_fn(params, target_params, batch, weights):
            n = batch[sb.OBS].shape[0]
            rows = jnp.arange(n)
            logits = dist_logits(params, batch[sb.OBS])
            logp_taken = jax.nn.log_softmax(
                logits[rows, batch[sb.ACTIONS]], -1)          # [B, N]
            # Greedy next action by expected value (double-Q: online net
            # selects, target net evaluates the distribution).
            next_t = dist_logits(target_params, batch[sb.NEXT_OBS])
            next_sel = (dist_logits(params, batch[sb.NEXT_OBS])
                        if double_q else next_t)
            q_next = (jax.nn.softmax(next_sel, -1) * z).sum(-1)
            a_next = q_next.argmax(-1)
            p_next = jax.nn.softmax(next_t[rows, a_next], -1)  # [B, N]
            # Bellman-shift the support and project onto the fixed atoms.
            not_done = (1.0
                        - batch[sb.TERMINATEDS].astype(jnp.float32))[:, None]
            tz = jnp.clip(
                batch[sb.REWARDS][:, None]
                + batch[NSTEP_GAMMAS][:, None] * not_done * z[None, :],
                v_min, v_max)
            b = (tz - v_min) / dz                              # [B, N]
            low = jnp.floor(b).astype(jnp.int32)
            high = jnp.ceil(b).astype(jnp.int32)
            # When b lands exactly on an atom (low == high) all mass goes
            # to that atom via the `low` scatter.
            w_low = jnp.where(low == high, 1.0, high - b)
            w_high = b - low
            proj = jnp.zeros((n, n_atoms))
            proj = proj.at[rows[:, None], low].add(p_next * w_low)
            proj = proj.at[rows[:, None], high].add(p_next * w_high)
            proj = jax.lax.stop_gradient(proj)
            ce = -(proj * logp_taken).sum(-1)                  # [B]
            return (weights * ce).mean(), ce

        def update(params, target_params, opt_state, batch, weights):
            (loss, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch, weights)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, ce

        self._jit_update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(batch[k]) for k in
              (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS,
               sb.TERMINATEDS)}
        jb[NSTEP_GAMMAS] = (jnp.asarray(batch[NSTEP_GAMMAS])
                            if NSTEP_GAMMAS in batch
                            else jnp.full(len(batch), self._gamma,
                                          jnp.float32))
        weights = jnp.asarray(batch["weights"]) if "weights" in batch \
            else jnp.ones(len(batch), jnp.float32)
        self.params, self.opt_state, loss, ce = self._jit_update(
            self.params, self.target_params, self.opt_state, jb, weights)
        # Cross-entropy doubles as the PER priority (the reference uses
        # the same signal for distributional Q).
        return {"td_error": np.asarray(ce), "loss": float(loss)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params


class C51(DQN):
    config_class = C51Config
    supports_model_config = False  # custom head, not catalog-built

    def _runner_class(self):
        return C51Runner

    def _extra_runner_kwargs(self) -> Dict[str, Any]:
        cfg = self.algo_config
        return {"n_atoms": cfg.n_atoms, "v_min": cfg.v_min,
                "v_max": cfg.v_max}

    def _make_q_learner(self, probe):
        cfg = self.algo_config
        return C51Learner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, gamma=cfg.gamma, n_atoms=cfg.n_atoms,
            v_min=cfg.v_min, v_max=cfg.v_max, double_q=cfg.double_q,
            seed=cfg.seed)
