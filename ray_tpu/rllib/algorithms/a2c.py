"""A2C: synchronous advantage actor-critic.

Reference parity: rllib/algorithms/a2c/a2c.py — the PPO pipeline minus
importance ratios and clipping: vanilla policy gradient with the GAE
advantage baseline the EnvRunners already compute. Reuses the whole PPO
harness (rollout fan-out, minibatch/epoch SGD, broadcast, multi-agent,
checkpointing); only the policy-gradient term differs.
"""

from __future__ import annotations

from ray_tpu.rllib.catalog import obs_shape_of
from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO
from ray_tpu.rllib.learner import PPOLearner


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lambda_ = 1.0           # reference A2C default (full GAE off)
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 1          # on-policy default: one fresh pass

    def training(self, *, lambda_=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kw) -> "A2CConfig":
        super().training(**kw)
        if lambda_ is not None:
            self.lambda_ = lambda_
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class A2CLearner(PPOLearner):
    """PPOLearner with the vanilla advantage policy gradient (no
    importance ratio / clipping); minibatch/epoch handling inherited."""

    def _pg_loss(self, logp, old_logp, adv):
        return -(logp * adv).mean()


class A2C(PPO):
    """Shares PPO's rollout fan-out/broadcast harness; swaps the learner."""

    config_class = A2CConfig

    def _make_learner(self, probe, seed_offset: int = 0):
        cfg = self.algo_config
        return A2CLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, vf_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff, seed=cfg.seed + seed_offset,
            obs_shape=obs_shape_of(probe),
            model=None if cfg.is_multi_agent else cfg.model,
            seq_len=cfg.rollout_fragment_length)
