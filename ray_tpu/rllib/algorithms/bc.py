"""Behavior Cloning: supervised policy learning from offline data.

Reference parity: rllib/algorithms/bc/bc.py (BC over the offline
JsonReader pipeline — no environment interaction during training;
evaluation rollouts are opt-in via evaluate()).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import policy_value_apply, policy_value_init
from ray_tpu.rllib.offline import JsonReader


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.input_path = ""          # dir of JsonWriter output
        self.train_batch_size = 256
        self.num_env_runners = 0      # offline: no rollout actors

    def offline_data(self, *, input_path=None) -> "BCConfig":
        if input_path is not None:
            self.input_path = input_path
        return self


class BC(Algorithm):
    config_class = BCConfig

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if not cfg.input_path:
            raise ValueError("BC requires config.offline_data(input_path=...)")
        self.env_runners = []
        self._episode_rewards = []
        self.reader = JsonReader(cfg.input_path, seed=cfg.seed)
        self.data = self.reader.read_all()
        self.build_learner()

    def build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.params = policy_value_init(
            jax.random.PRNGKey(cfg.seed), probe.observation_dim,
            probe.num_actions, hidden=cfg.hidden)
        self._optimizer = optax.adam(cfg.lr)
        self.opt_state = self._optimizer.init(self.params)

        def loss_fn(params, obs, actions):
            logits, _ = policy_value_apply(params, obs)
            logp = jax.nn.log_softmax(logits)
            n = logits.shape[0]
            return -logp[jnp.arange(n), actions].mean()

        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions)
            updates, opt_state = self._optimizer.update(grads, opt_state,
                                                        params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._jit_update = jax.jit(update)
        self._rng = np.random.RandomState(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        cfg = self.algo_config
        n = len(self.data)
        idx = self._rng.randint(0, n, size=min(cfg.train_batch_size, n))
        obs = jnp.asarray(self.data[sb.OBS][idx])
        actions = jnp.asarray(self.data[sb.ACTIONS][idx])
        self.params, self.opt_state, loss = self._jit_update(
            self.params, self.opt_state, obs, actions)
        return {"loss": float(loss), "num_samples_trained": int(len(idx)),
                "episode_reward_mean": float("nan")}

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy rollouts with the cloned policy."""
        import jax
        cfg = self.algo_config
        env = make_env(cfg.env, cfg.env_config)
        fwd = jax.jit(policy_value_apply)
        rewards = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=cfg.seed + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = fwd(self.params, obs[None, :])
                a = int(np.argmax(np.asarray(logits)[0]))
                obs, r, term, trunc, _ = env.step(a)
                total += r
                done = term or trunc
            rewards.append(total)
        return {"evaluation_reward_mean": float(np.mean(rewards))}

    def save_checkpoint(self):
        return {"params": self.params, "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.params = ckpt["params"]
        self._iteration = ckpt.get("iteration", 0)

    def cleanup(self):
        pass
