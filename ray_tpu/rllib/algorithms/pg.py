"""PG: vanilla REINFORCE policy gradient.

Reference parity: rllib/algorithms/pg — the simplest on-policy algorithm:
the gradient weights each action's log-prob by the empirical discounted
return (no importance ratio, no clipping, no advantage baseline). Shares
the PPO rollout harness; the runners' GAE runs with lambda=1 so
VALUE_TARGETS is exactly the Monte-Carlo return (bootstrapped by V only
where a fragment truncates mid-episode — the value head is trained for
that tail bootstrap but is NOT used as a baseline).
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.catalog import obs_shape_of
from ray_tpu.rllib.algorithm import AlgorithmConfig
from ray_tpu.rllib.algorithms.a2c import A2CLearner
from ray_tpu.rllib.algorithms.ppo import PPO
from ray_tpu.rllib.sample_batch import concat_samples


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self.lambda_ = 1.0          # Monte-Carlo returns
        self.vf_loss_coeff = 0.5    # V trains only for truncation bootstrap
        self.entropy_coeff = 0.0
        self.num_epochs = 1         # one pass: the gradient is on-policy

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 **kw) -> "PGConfig":
        super().training(**kw)
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PGLearner(A2CLearner):
    """A2C's vanilla -logp*adv gradient; PG feeds it returns instead of
    advantages (the whitening in the shared loss is a constant baseline,
    which keeps the REINFORCE gradient unbiased)."""


class PG(PPO):
    config_class = PGConfig

    def _make_learner(self, probe, seed_offset: int = 0):
        cfg = self.algo_config
        return PGLearner(
            probe.observation_dim, probe.num_actions, hidden=cfg.hidden,
            lr=cfg.lr, vf_coeff=cfg.vf_loss_coeff,
            entropy_coeff=cfg.entropy_coeff, seed=cfg.seed + seed_offset,
            obs_shape=obs_shape_of(probe),
            model=None if cfg.is_multi_agent else cfg.model,
            seq_len=cfg.rollout_fragment_length)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        if cfg.is_multi_agent:
            raise NotImplementedError(
                "PG is single-policy; use A2C/PPO for multi-agent")
        batch = concat_samples(ray_tpu.get(self.sample_all_runners()))
        # REINFORCE: weight log-probs by the return, not the GAE advantage.
        batch[sb.ADVANTAGES] = batch[sb.VALUE_TARGETS]
        metrics = self.learner.update(
            batch, minibatch_size=min(cfg.minibatch_size, len(batch)),
            num_epochs=cfg.num_epochs, seed=cfg.seed + self._iteration)
        self.broadcast_weights(self.learner.get_weights())
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics
