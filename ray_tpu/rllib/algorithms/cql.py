"""CQL: Conservative Q-Learning for offline continuous control.

Reference parity: rllib/algorithms/cql/cql.py (+ cql_torch_policy loss —
Kumar et al. 2020): SAC machinery trained purely from an offline dataset,
with a conservative regularizer that pushes down Q on out-of-distribution
actions (logsumexp over sampled actions) and up on dataset actions.

TPU-first: the conservative logsumexp is vectorised over `num_ood_actions`
uniform + policy samples in one batched twin-Q evaluation inside the same
jitted update as the SAC losses.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.offline import JsonReader
from ray_tpu.rllib.sample_batch import SampleBatch


class CQLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.env = "Pendulum-v1"
        self.input_path = ""
        self.tau = 0.005
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.initial_alpha = 1.0
        self.target_entropy = None
        self.cql_alpha = 1.0            # conservative penalty weight
        self.num_ood_actions = 4        # sampled actions per state for lse
        self.train_batch_size = 256
        self.num_env_runners = 0        # offline: no rollout actors

    def offline_data(self, *, input_path=None) -> "CQLConfig":
        if input_path is not None:
            self.input_path = input_path
        return self

    def training(self, *, tau=None, actor_lr=None, critic_lr=None,
                 alpha_lr=None, cql_alpha=None, num_ood_actions=None,
                 **kw) -> "CQLConfig":
        super().training(**kw)
        for name, v in (("tau", tau), ("actor_lr", actor_lr),
                        ("critic_lr", critic_lr), ("alpha_lr", alpha_lr),
                        ("cql_alpha", cql_alpha),
                        ("num_ood_actions", num_ood_actions)):
            if v is not None:
                setattr(self, name, v)
        return self


class CQLLearner:
    """SAC update + conservative penalty, one jitted function."""

    def __init__(self, obs_dim: int, action_dim: int, low: float,
                 high: float, *, hidden=(64, 64), actor_lr=3e-4,
                 critic_lr=3e-4, alpha_lr=3e-4, gamma=0.99, tau=0.005,
                 initial_alpha=1.0, target_entropy=None, cql_alpha=1.0,
                 num_ood_actions=4, seed=0):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.models import (squashed_gaussian_init,
                                          squashed_gaussian_sample,
                                          twin_q_apply, twin_q_init)
        if target_entropy is None:
            target_entropy = -float(action_dim)
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.state = {
            "actor": squashed_gaussian_init(k1, obs_dim, action_dim,
                                            hidden=tuple(hidden)),
            "critic": twin_q_init(k2, obs_dim, action_dim,
                                  hidden=tuple(hidden)),
            "log_alpha": jnp.log(jnp.float32(initial_alpha)),
        }
        self.state["target_critic"] = jax.tree_util.tree_map(
            lambda x: x, self.state["critic"])
        self._opt_actor = optax.adam(actor_lr)
        self._opt_critic = optax.adam(critic_lr)
        self._opt_alpha = optax.adam(alpha_lr)
        self.opt_state = {
            "actor": self._opt_actor.init(self.state["actor"]),
            "critic": self._opt_critic.init(self.state["critic"]),
            "alpha": self._opt_alpha.init(self.state["log_alpha"]),
        }
        n_ood = num_ood_actions

        def _q_on_sampled(critic, obs, actions):
            """actions: [n, B, A] -> stacked (q1, q2): [n, B] each."""
            def one(a):
                return twin_q_apply(critic, obs, a)
            q1s, q2s = jax.vmap(one)(actions)
            return q1s, q2s

        def critic_loss(critic, state, batch, rng):
            r_td, r_ood, r_pi, r_pi2 = jax.random.split(rng, 4)
            # --- standard SAC TD target
            a2, logp2 = squashed_gaussian_sample(
                r_td, state["actor"], batch[sb.NEXT_OBS], low, high)
            tq1, tq2 = twin_q_apply(state["target_critic"],
                                    batch[sb.NEXT_OBS], a2)
            alpha = jnp.exp(state["log_alpha"])
            target = batch[sb.REWARDS] + gamma * (
                1.0 - batch[sb.TERMINATEDS]) * (
                    jnp.minimum(tq1, tq2) - alpha * logp2)
            target = jax.lax.stop_gradient(target)
            q1, q2 = twin_q_apply(critic, batch[sb.OBS], batch[sb.ACTIONS])
            td = ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

            # --- conservative regularizer: logsumexp over OOD actions
            B = batch[sb.OBS].shape[0]
            a_dim = batch[sb.ACTIONS].shape[-1]
            rand_a = jax.random.uniform(r_ood, (n_ood, B, a_dim),
                                        minval=low, maxval=high)
            pi_a, _ = squashed_gaussian_sample(
                r_pi, state["actor"],
                jnp.broadcast_to(batch[sb.OBS], (n_ood, B, obs_dim)
                                 ).reshape(n_ood * B, obs_dim), low, high)
            pi_a = pi_a.reshape(n_ood, B, a_dim)
            cat = jnp.concatenate([rand_a, pi_a], axis=0)   # [2n, B, A]
            cq1, cq2 = _q_on_sampled(critic, batch[sb.OBS], cat)
            lse1 = jax.nn.logsumexp(cq1, axis=0)
            lse2 = jax.nn.logsumexp(cq2, axis=0)
            conservative = ((lse1 - q1) + (lse2 - q2)).mean()
            return td + cql_alpha * conservative, (
                0.5 * (q1.mean() + q2.mean()), conservative)

        def actor_loss(actor, state, batch, rng):
            a, logp = squashed_gaussian_sample(rng, actor, batch[sb.OBS],
                                               low, high)
            q1, q2 = twin_q_apply(state["critic"], batch[sb.OBS], a)
            alpha = jnp.exp(state["log_alpha"])
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp.mean()

        def alpha_loss(log_alpha, mean_logp):
            return -(log_alpha * jax.lax.stop_gradient(
                mean_logp + target_entropy))

        def update(state, opt_state, batch, rng):
            rng_c, rng_a = jax.random.split(rng)
            (c_loss, (q_mean, gap)), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"], state, batch,
                                           rng_c)
            upd, opt_state["critic"] = self._opt_critic.update(
                c_grads, opt_state["critic"], state["critic"])
            state["critic"] = optax.apply_updates(state["critic"], upd)

            (a_loss, mean_logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state["actor"], state, batch,
                                          rng_a)
            upd, opt_state["actor"] = self._opt_actor.update(
                a_grads, opt_state["actor"], state["actor"])
            state["actor"] = optax.apply_updates(state["actor"], upd)

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                state["log_alpha"], mean_logp)
            upd, opt_state["alpha"] = self._opt_alpha.update(
                al_grad, opt_state["alpha"], state["log_alpha"])
            state["log_alpha"] = optax.apply_updates(state["log_alpha"], upd)

            state["target_critic"] = jax.tree_util.tree_map(
                lambda t, s: (1 - tau) * t + tau * s,
                state["target_critic"], state["critic"])
            return state, opt_state, {
                "critic_loss": c_loss, "actor_loss": a_loss,
                "cql_gap": gap, "mean_q": q_mean,
                "alpha": jnp.exp(state["log_alpha"]),
            }

        self._jit_update = jax.jit(update)
        self._key = jax.random.PRNGKey(seed + 1)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp
        jb = {
            sb.OBS: jnp.asarray(batch[sb.OBS], jnp.float32),
            sb.ACTIONS: jnp.asarray(batch[sb.ACTIONS],
                                    jnp.float32).reshape(len(batch), -1),
            sb.REWARDS: jnp.asarray(batch[sb.REWARDS], jnp.float32),
            sb.NEXT_OBS: jnp.asarray(batch[sb.NEXT_OBS], jnp.float32),
            sb.TERMINATEDS: jnp.asarray(batch[sb.TERMINATEDS], jnp.float32),
        }
        self._key, sub = jax.random.split(self._key)
        self.state, self.opt_state, m = self._jit_update(
            self.state, self.opt_state, jb, sub)
        return {k: float(v) for k, v in m.items()}

    def get_weights(self):
        return self.state

    def set_weights(self, state):
        self.state = state


class CQL(Algorithm):
    config_class = CQLConfig

    def setup(self, config: Dict[str, Any]):
        cfg = self.algo_config
        if not cfg.input_path:
            raise ValueError(
                "CQL requires config.offline_data(input_path=...)")
        self.env_runners = []
        self._episode_rewards = []
        self.reader = JsonReader(cfg.input_path, seed=cfg.seed)
        self.data = self.reader.read_all()
        self._rng = np.random.RandomState(cfg.seed)
        self.build_learner()

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = CQLLearner(
            probe.observation_dim, probe.action_dim, probe.action_low,
            probe.action_high, hidden=cfg.hidden, actor_lr=cfg.actor_lr,
            critic_lr=cfg.critic_lr, alpha_lr=cfg.alpha_lr,
            gamma=cfg.gamma, tau=cfg.tau,
            initial_alpha=cfg.initial_alpha,
            target_entropy=cfg.target_entropy, cql_alpha=cfg.cql_alpha,
            num_ood_actions=cfg.num_ood_actions, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        n = len(self.data)
        idx = self._rng.randint(0, n, size=min(cfg.train_batch_size, n))
        batch = SampleBatch({k: v[idx] for k, v in self.data.items()})
        m = self.learner.update(batch)
        m["num_samples_trained"] = int(len(idx))
        m["episode_reward_mean"] = float("nan")
        return m

    def save_checkpoint(self):
        return {"state": self.learner.get_weights(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["state"])
        self._iteration = ckpt.get("iteration", 0)

    def cleanup(self):
        pass
