"""APPO: asynchronous PPO.

Reference parity: rllib/algorithms/appo/appo.py — IMPALA's pipelined
architecture (consume whichever rollout lands first, re-dispatch the
runner immediately) with the PPO surrogate objective and multiple SGD
epochs per batch plus a periodically-refreshed behavior anchor (the
reference's target network) to bound off-policy drift.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib.algorithms.impala import Impala, ImpalaConfig


class APPOConfig(ImpalaConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.num_epochs = 2            # unlike IMPALA's single pass
        self.target_update_frequency = 4

    def training(self, *, target_update_frequency=None, **kw) -> "APPOConfig":
        super().training(**kw)
        if target_update_frequency is not None:
            self.target_update_frequency = target_update_frequency
        return self


class APPO(Impala):
    config_class = APPOConfig

    def setup(self, config):
        super().setup(config)
        self._batches_since_target = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        metrics: Dict[str, Any] = {}
        steps = 0
        for _ in range(cfg.num_batches_per_step):
            done, _ = ray_tpu.wait(list(self._inflight.keys()),
                                   num_returns=1, timeout=60.0)
            if not done:
                break
            ref = done[0]
            runner = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            self._inflight[runner.sample.remote(
                cfg.rollout_fragment_length, cfg.gamma,
                self.gae_lambda())] = runner
            # PPO-style multi-epoch minibatch SGD on the async batch; the
            # clip term bounds the off-policy drift the pipelining causes.
            m = self.learner.update(
                batch, minibatch_size=min(cfg.minibatch_size, len(batch)),
                num_epochs=cfg.num_epochs, seed=cfg.seed + self._iteration)
            steps += len(batch)
            metrics.update(m)
            self._batches_since_target += 1
            if self._batches_since_target >= cfg.target_update_frequency:
                # Refresh the behavior anchor everywhere (the reference
                # updates its target net + broadcasts on the same cadence).
                params = self.learner.get_weights()
                for er in self.env_runners:
                    er.set_weights.remote(params)
                self._batches_since_target = 0
        metrics["num_env_steps_sampled"] = steps
        return metrics
