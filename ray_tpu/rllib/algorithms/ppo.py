"""PPO (reference: rllib/algorithms/ppo/ppo.py:405 training_step).

Synchronous: fan out rollouts to all EnvRunners, GAE on the runners,
minibatch-SGD the jitted learner, broadcast weights.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib.catalog import obs_shape_of
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.learner import PPOLearner
from ray_tpu.rllib.sample_batch import concat_samples


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0

    def training(self, *, lambda_=None, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kw) -> "PPOConfig":
        super().training(**kw)
        if lambda_ is not None:
            self.lambda_ = lambda_
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PPO(Algorithm):
    config_class = PPOConfig
    supports_model_config = True

    def _make_learner(self, probe, seed_offset: int = 0):
        cfg = self.algo_config
        return PPOLearner(
            probe.observation_dim, probe.num_actions,
            hidden=cfg.hidden, lr=cfg.lr,
            clip_param=getattr(cfg, "clip_param", 0.2),
            vf_coeff=getattr(cfg, "vf_loss_coeff", 0.5),
            entropy_coeff=getattr(cfg, "entropy_coeff", 0.0),
            seed=cfg.seed + seed_offset,
            obs_shape=obs_shape_of(probe),
            # MultiAgentEnvRunner builds the legacy MLP; the catalog path
            # is single-agent (matches runner-side construction).
            model=None if cfg.is_multi_agent else cfg.model,
            seq_len=cfg.rollout_fragment_length)

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        if cfg.is_multi_agent:
            # One learner per policy (reference: Learner per module in the
            # MultiRLModule); distinct seeds so policies don't start as
            # clones; weights broadcast as a policy-keyed dict.
            self.learners = {pid: self._make_learner(probe, seed_offset=j)
                             for j, pid in enumerate(cfg.policies)}
            self.broadcast_weights({pid: ln.get_weights()
                                    for pid, ln in self.learners.items()})
        else:
            self.learner = self._make_learner(probe)
            self.broadcast_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        if cfg.is_multi_agent:
            return self._multi_agent_training_step()
        batch = concat_samples(ray_tpu.get(self.sample_all_runners()))
        metrics = self.learner.update(
            batch, minibatch_size=min(cfg.minibatch_size, len(batch)),
            num_epochs=cfg.num_epochs, seed=cfg.seed + self._iteration)
        self.broadcast_weights(self.learner.get_weights())
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics

    def _multi_agent_training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.sample_batch import MultiAgentBatch
        cfg = self.algo_config
        ma = MultiAgentBatch.concat_samples(
            ray_tpu.get(self.sample_all_runners()))
        metrics: Dict[str, Any] = {}
        for pid, pbatch in ma.policy_batches.items():
            if not len(pbatch):
                continue
            m = self.learners[pid].update(
                pbatch, minibatch_size=min(cfg.minibatch_size, len(pbatch)),
                num_epochs=cfg.num_epochs, seed=cfg.seed + self._iteration)
            for k, v in m.items():
                metrics[f"{pid}/{k}"] = v
        self.broadcast_weights({pid: ln.get_weights()
                                for pid, ln in self.learners.items()})
        metrics["num_env_steps_sampled"] = ma.env_steps()
        metrics["num_agent_steps_sampled"] = ma.agent_steps()
        return metrics

    def save_checkpoint(self):
        if self.algo_config.is_multi_agent:
            return {"params": {pid: ln.get_weights()
                               for pid, ln in self.learners.items()},
                    "iteration": self._iteration}
        return {"params": self.learner.get_weights(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        if self.algo_config.is_multi_agent:
            for pid, w in ckpt["params"].items():
                self.learners[pid].set_weights(w)
            self._iteration = ckpt.get("iteration", 0)
            self.broadcast_weights({pid: ln.get_weights()
                                    for pid, ln in self.learners.items()})
            return
        self.learner.set_weights(ckpt["params"])
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_weights())
