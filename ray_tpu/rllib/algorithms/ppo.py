"""PPO (reference: rllib/algorithms/ppo/ppo.py:405 training_step).

Synchronous: fan out rollouts to all EnvRunners, GAE on the runners,
minibatch-SGD the jitted learner, broadcast weights.
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.learner import PPOLearner
from ray_tpu.rllib.sample_batch import concat_samples


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0

    def training(self, *, lambda_=None, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kw) -> "PPOConfig":
        super().training(**kw)
        if lambda_ is not None:
            self.lambda_ = lambda_
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PPO(Algorithm):
    config_class = PPOConfig

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = PPOLearner(
            probe.observation_dim, probe.num_actions,
            hidden=cfg.hidden, lr=cfg.lr,
            clip_param=getattr(cfg, "clip_param", 0.2),
            vf_coeff=getattr(cfg, "vf_loss_coeff", 0.5),
            entropy_coeff=getattr(cfg, "entropy_coeff", 0.0),
            seed=cfg.seed)
        self.broadcast_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        batch = concat_samples(ray_tpu.get(self.sample_all_runners()))
        metrics = self.learner.update(
            batch, minibatch_size=min(cfg.minibatch_size, len(batch)),
            num_epochs=cfg.num_epochs, seed=cfg.seed + self._iteration)
        self.broadcast_weights(self.learner.get_weights())
        metrics["num_env_steps_sampled"] = len(batch)
        return metrics

    def save_checkpoint(self):
        return {"params": self.learner.get_weights(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["params"])
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_weights())
