"""APEX-DQN: distributed prioritized experience replay.

Reference parity: rllib/algorithms/apex_dqn (Horgan et al. 2018) — the
three decoupled roles:

  - many EnvRunner actors explore with a PER-WORKER epsilon ladder
    (eps_i = eps ** (1 + i/(K-1) * alpha), the reference's
    per-worker-exploration schedule), sampling concurrently;
  - a ReplayActor owns the prioritized buffer, absorbing rollouts and
    serving training batches;
  - the learner trains WHILE rollouts are in flight: training_step kicks
    off all sample_transitions calls, runs its replay updates, and only
    then collects the rollout refs — sampling and learning overlap
    instead of alternating (the reference's asynchronous pipeline,
    expressed as futures rather than background threads).

The Q-learner itself is DQNLearner (double-Q, target net) unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.replay_buffer import PrioritizedReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayActor:
    """Actor wrapper around PrioritizedReplayBuffer (reference:
    apex_dqn's ReplayActor sharding; one shard here — shard by spawning
    several and round-robining adds)."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        self._buf = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                            seed=seed)

    def add(self, batch: SampleBatch) -> int:
        self._buf.add(batch)
        return len(self._buf)

    def sample(self, n: int, beta: float = 0.4) -> SampleBatch:
        return self._buf.sample(n, beta=beta)

    def update_priorities(self, idx, prios):
        self._buf.update_priorities(np.asarray(idx), np.asarray(prios))

    def size(self) -> int:
        return len(self._buf)


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_env_runners = 4
        self.per_worker_eps_alpha = 7.0   # exploration ladder exponent
        self.epsilon_start = 0.4          # ladder base (reference default)
        self.epsilon_end = 0.0            # ladder is static, not decayed
        self.prioritized_replay = True

    def training(self, *, per_worker_eps_alpha=None, **kw) -> "ApexDQNConfig":
        super().training(**kw)
        if per_worker_eps_alpha is not None:
            self.per_worker_eps_alpha = per_worker_eps_alpha
        return self


class ApexDQN(DQN):
    config_class = ApexDQNConfig

    def build_learner(self):
        from ray_tpu.rllib.env import make_env
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = self._make_q_learner(probe)
        self.replay_actor = ray_tpu.remote(num_cpus=0)(ReplayActor).remote(
            cfg.replay_buffer_capacity, seed=cfg.seed)
        self._steps_sampled = 0
        self._last_target_sync = 0
        k = max(1, cfg.num_env_runners)
        a = cfg.per_worker_eps_alpha
        self._worker_eps: List[float] = [
            cfg.epsilon_start ** (1 + (i / max(1, k - 1)) * a)
            for i in range(k)]
        self.broadcast_weights(self.learner.get_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        # 1) launch all rollouts (don't wait).
        rollout_refs = [
            er.sample_transitions.remote(cfg.rollout_fragment_length,
                                         self._worker_eps[i])
            for i, er in enumerate(self.env_runners)]
        # 2) train from the replay actor while those are in flight,
        # prefetching batch i+1 during update(batch i) so the learner
        # never idles on an actor round-trip.
        metrics: Dict[str, Any] = {}
        size = ray_tpu.get(self.replay_actor.size.remote())
        if size >= cfg.learning_starts:
            losses = []
            next_ref = self.replay_actor.sample.remote(cfg.train_batch_size)
            for i in range(cfg.updates_per_step):
                replayed = ray_tpu.get(next_ref)
                if i + 1 < cfg.updates_per_step:
                    next_ref = self.replay_actor.sample.remote(
                        cfg.train_batch_size)
                if not len(replayed):
                    break
                m = self.learner.update(replayed)
                if "batch_indexes" in replayed:
                    self.replay_actor.update_priorities.remote(
                        replayed["batch_indexes"], m["td_error"] + 1e-6)
                losses.append(m["loss"])
            if losses:
                metrics["loss"] = float(np.mean(losses))
            self.broadcast_weights(self.learner.get_weights())
        # 3) collect rollouts into the replay actor.
        from ray_tpu.rllib.algorithms.dqn import nstep_transform
        add_refs = []
        steps_this_iter = 0
        for ref in rollout_refs:
            batch = ray_tpu.get(ref)
            steps_this_iter += len(batch)
            if cfg.n_step > 1:
                batch = nstep_transform(batch, cfg.n_step, cfg.gamma,
                                        cfg.num_envs_per_env_runner)
            add_refs.append(self.replay_actor.add.remote(batch))
        self._steps_sampled += steps_this_iter
        replay_size = max(ray_tpu.get(add_refs)) if add_refs else 0
        if (self._steps_sampled - self._last_target_sync
                >= cfg.target_network_update_freq):
            self.learner.sync_target()
            self._last_target_sync = self._steps_sampled
        metrics.update({
            "replay_size": replay_size,
            "num_env_steps_sampled": steps_this_iter,
            "num_env_steps_sampled_lifetime": self._steps_sampled,
            "worker_epsilons": list(np.round(self._worker_eps, 4)),
        })
        return metrics

    def cleanup(self):
        super().cleanup()
        try:
            ray_tpu.kill(self.replay_actor)
        except Exception:
            pass
