"""SAC: Soft Actor-Critic for continuous control.

Reference parity: rllib/algorithms/sac/sac.py (+ sac_torch_policy losses):
tanh-squashed Gaussian actor, clipped double-Q critics with Polyak-averaged
targets, and automatic entropy-temperature tuning (target entropy
-action_dim). The whole update (critic + actor + alpha + target sync) is
ONE jitted JAX function; collection runs on ContinuousEnvRunner actors.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)
from ray_tpu.rllib.sample_batch import SampleBatch, concat_samples


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.env = "Pendulum-v1"
        self.tau = 0.005
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.initial_alpha = 1.0
        self.target_entropy = None          # None => -action_dim
        self.buffer_capacity = 100_000
        self.random_warmup_steps = 500
        self.grad_steps_per_iter = 0        # 0 => one per sampled step
        self.train_batch_size = 256
        self.rollout_fragment_length = 64
        # Prioritized experience replay (reference: sac.py
        # replay_buffer_config prioritized_replay*): proportional
        # priorities from |TD error|, importance weights into the
        # critic loss.
        self.prioritized_replay = False
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4

    def training(self, *, tau=None, actor_lr=None, critic_lr=None,
                 alpha_lr=None, initial_alpha=None, target_entropy=None,
                 buffer_capacity=None, random_warmup_steps=None,
                 grad_steps_per_iter=None, prioritized_replay=None,
                 prioritized_replay_alpha=None,
                 prioritized_replay_beta=None, **kw) -> "SACConfig":
        super().training(**kw)
        for name, v in (("tau", tau), ("actor_lr", actor_lr),
                        ("critic_lr", critic_lr), ("alpha_lr", alpha_lr),
                        ("initial_alpha", initial_alpha),
                        ("target_entropy", target_entropy),
                        ("buffer_capacity", buffer_capacity),
                        ("random_warmup_steps", random_warmup_steps),
                        ("grad_steps_per_iter", grad_steps_per_iter),
                        ("prioritized_replay", prioritized_replay),
                        ("prioritized_replay_alpha",
                         prioritized_replay_alpha),
                        ("prioritized_replay_beta",
                         prioritized_replay_beta)):
            if v is not None:
                setattr(self, name, v)
        return self


class SACLearner:
    """One jitted SAC update: critic TD + actor reparameterized + alpha."""

    def __init__(self, obs_dim: int, action_dim: int, low: float,
                 high: float, *, hidden=(64, 64), actor_lr=3e-4,
                 critic_lr=3e-4, alpha_lr=3e-4, gamma=0.99, tau=0.005,
                 initial_alpha=1.0, target_entropy=None, seed=0):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.models import (squashed_gaussian_init,
                                          squashed_gaussian_sample,
                                          twin_q_init, twin_q_apply)
        if target_entropy is None:
            target_entropy = -float(action_dim)
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.state = {
            "actor": squashed_gaussian_init(k1, obs_dim, action_dim,
                                            hidden=tuple(hidden)),
            "critic": twin_q_init(k2, obs_dim, action_dim,
                                  hidden=tuple(hidden)),
            "log_alpha": jnp.log(jnp.float32(initial_alpha)),
        }
        self.state["target_critic"] = jax.tree_util.tree_map(
            lambda x: x, self.state["critic"])
        self._opt_actor = optax.adam(actor_lr)
        self._opt_critic = optax.adam(critic_lr)
        self._opt_alpha = optax.adam(alpha_lr)
        self.opt_state = {
            "actor": self._opt_actor.init(self.state["actor"]),
            "critic": self._opt_critic.init(self.state["critic"]),
            "alpha": self._opt_alpha.init(self.state["log_alpha"]),
        }

        def critic_loss(critic, state, batch, rng):
            a2, logp2 = squashed_gaussian_sample(
                rng, state["actor"], batch[sb.NEXT_OBS], low, high)
            tq1, tq2 = twin_q_apply(state["target_critic"],
                                    batch[sb.NEXT_OBS], a2)
            alpha = jnp.exp(state["log_alpha"])
            target = batch[sb.REWARDS] + gamma * (
                1.0 - batch[sb.TERMINATEDS]) * (
                    jnp.minimum(tq1, tq2) - alpha * logp2)
            target = jax.lax.stop_gradient(target)
            q1, q2 = twin_q_apply(critic, batch[sb.OBS], batch[sb.ACTIONS])
            # Per-sample importance weights (PER; ones when uniform) and
            # |TD| out for priority updates.
            w = batch["weights"]
            td = jnp.abs(q1 - target)
            loss = (w * ((q1 - target) ** 2 + (q2 - target) ** 2)).mean()
            return loss, (0.5 * (q1.mean() + q2.mean()), td)

        def actor_loss(actor, state, batch, rng):
            a, logp = squashed_gaussian_sample(rng, actor, batch[sb.OBS],
                                               low, high)
            q1, q2 = twin_q_apply(state["critic"], batch[sb.OBS], a)
            alpha = jnp.exp(state["log_alpha"])
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp.mean()

        def alpha_loss(log_alpha, mean_logp):
            return -(log_alpha * jax.lax.stop_gradient(
                mean_logp + target_entropy))

        def update(state, opt_state, batch, rng):
            rng_c, rng_a = jax.random.split(rng)
            (c_loss, (q_mean, td)), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True)(state["critic"], state, batch,
                                           rng_c)
            upd, opt_state["critic"] = self._opt_critic.update(
                c_grads, opt_state["critic"], state["critic"])
            state["critic"] = optax.apply_updates(state["critic"], upd)

            (a_loss, mean_logp), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(state["actor"], state, batch,
                                          rng_a)
            upd, opt_state["actor"] = self._opt_actor.update(
                a_grads, opt_state["actor"], state["actor"])
            state["actor"] = optax.apply_updates(state["actor"], upd)

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                state["log_alpha"], mean_logp)
            upd, opt_state["alpha"] = self._opt_alpha.update(
                al_grad, opt_state["alpha"], state["log_alpha"])
            state["log_alpha"] = optax.apply_updates(state["log_alpha"],
                                                     upd)

            state["target_critic"] = jax.tree_util.tree_map(
                lambda t, s: (1 - tau) * t + tau * s,
                state["target_critic"], state["critic"])
            return state, opt_state, {
                "critic_loss": c_loss, "actor_loss": a_loss,
                "alpha_loss": al_loss, "alpha": jnp.exp(state["log_alpha"]),
                "mean_q": q_mean, "entropy": -mean_logp,
            }, td

        self._jit_update = jax.jit(update)
        self._key = jax.random.PRNGKey(seed + 1)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp
        import numpy as _np
        w = batch["weights"] if "weights" in batch.keys() else \
            _np.ones(len(batch), _np.float32)
        jb = {
            sb.OBS: jnp.asarray(batch[sb.OBS], jnp.float32),
            sb.ACTIONS: jnp.asarray(batch[sb.ACTIONS],
                                    jnp.float32).reshape(len(batch), -1),
            sb.REWARDS: jnp.asarray(batch[sb.REWARDS], jnp.float32),
            sb.NEXT_OBS: jnp.asarray(batch[sb.NEXT_OBS], jnp.float32),
            sb.TERMINATEDS: jnp.asarray(batch[sb.TERMINATEDS], jnp.float32),
            "weights": jnp.asarray(w, jnp.float32),
        }
        self._key, sub = jax.random.split(self._key)
        self.state, self.opt_state, m, td = self._jit_update(
            self.state, self.opt_state, jb, sub)
        self.last_td_error = _np.asarray(td)
        return {k: float(v) for k, v in m.items()}

    def get_actor_weights(self):
        return self.state["actor"]

    def get_weights(self):
        return self.state

    def set_weights(self, state):
        self.state = state


class SAC(Algorithm):
    config_class = SACConfig

    def setup(self, config: Dict[str, Any]):
        from ray_tpu.rllib.env import get_env_creator
        from ray_tpu.rllib.env_runner import ContinuousEnvRunner
        cfg = self.algo_config
        creator = get_env_creator(cfg.env)
        runner_cls = ray_tpu.remote(num_cpus=1)(ContinuousEnvRunner)
        self.env_runners = [
            runner_cls.remote(creator, cfg.env_config,
                              cfg.num_envs_per_env_runner,
                              seed=cfg.seed + 1000 * i, hidden=cfg.hidden,
                              obs_connectors=cfg.obs_connectors,
                              action_connectors=cfg.action_connectors)
            for i in range(cfg.num_env_runners)
        ]
        self._episode_rewards = []
        self._steps_sampled = 0
        if cfg.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(
                cfg.buffer_capacity, alpha=cfg.prioritized_replay_alpha,
                seed=cfg.seed)
        else:
            self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.build_learner()

    def build_learner(self):
        cfg = self.algo_config
        probe = make_env(cfg.env, cfg.env_config)
        self.learner = SACLearner(
            probe.observation_dim, probe.action_dim, probe.action_low,
            probe.action_high, hidden=cfg.hidden, actor_lr=cfg.actor_lr,
            critic_lr=cfg.critic_lr, alpha_lr=cfg.alpha_lr,
            gamma=cfg.gamma, tau=cfg.tau,
            initial_alpha=cfg.initial_alpha,
            target_entropy=cfg.target_entropy, seed=cfg.seed)
        self.broadcast_weights(self.learner.get_actor_weights())

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        refs = [er.sample_transitions.remote(
            cfg.rollout_fragment_length, cfg.random_warmup_steps,
            self._steps_sampled) for er in self.env_runners]
        batch = concat_samples(ray_tpu.get(refs))
        self.buffer.add(batch)
        self._steps_sampled += len(batch)
        grad_steps = cfg.grad_steps_per_iter or len(batch)
        metrics: Dict[str, Any] = {}
        if len(self.buffer) >= cfg.train_batch_size:
            per = cfg.prioritized_replay
            for _ in range(grad_steps):
                if per:
                    sample = self.buffer.sample(
                        cfg.train_batch_size,
                        beta=cfg.prioritized_replay_beta)
                else:
                    sample = self.buffer.sample(cfg.train_batch_size)
                m = self.learner.update(sample)
                if per:
                    self.buffer.update_priorities(
                        sample["batch_indexes"],
                        self.learner.last_td_error + 1e-6)
            metrics.update(m)
        self.broadcast_weights(self.learner.get_actor_weights())
        metrics["num_env_steps_sampled"] = self._steps_sampled
        metrics["buffer_size"] = len(self.buffer)
        return metrics

    def save_checkpoint(self):
        return {"state": self.learner.get_weights(),
                "iteration": self._iteration}

    def load_checkpoint(self, ckpt):
        self.learner.set_weights(ckpt["state"])
        self._iteration = ckpt.get("iteration", 0)
        self.broadcast_weights(self.learner.get_actor_weights())
