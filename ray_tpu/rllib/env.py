"""Environments: gym-style API + a dependency-free CartPole.

Reference parity: rllib/env/ (EnvRunner-compatible envs). The registry
mirrors rllib's tune.register_env; CartPole-v1 dynamics follow the classic
control formulation so learning curves are comparable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal gym-style interface: reset() -> (obs, info);
    step(a) -> (obs, reward, terminated, truncated, info)."""

    observation_dim: int
    # Image envs set the full shape, e.g. (H, W, C); flat envs leave it
    # empty and the catalog uses (observation_dim,).
    observation_shape: Tuple[int, ...] = ()
    num_actions: int
    # Continuous-control envs set these instead of num_actions.
    continuous: bool = False
    action_dim: int = 0
    action_low: float = -1.0
    action_high: float = 1.0

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError


class CartPoleEnv(Env):
    """CartPole-v1 (no gym dependency; same constants/termination)."""

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 500):
        self._rng = np.random.RandomState()
        self._max_steps = max_steps
        self._g = 9.8
        self._mc = 1.0
        self._mp = 0.1
        self._l = 0.5
        self._force = 10.0
        self._dt = 0.02
        self._theta_lim = 12 * 2 * np.pi / 360
        self._x_lim = 2.4
        self._state = None
        self._t = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32).copy(), {}

    def step(self, action):
        x, x_dot, th, th_dot = self._state
        force = self._force if action == 1 else -self._force
        costh, sinth = np.cos(th), np.sin(th)
        total_m = self._mc + self._mp
        pml = self._mp * self._l
        temp = (force + pml * th_dot ** 2 * sinth) / total_m
        th_acc = (self._g * sinth - costh * temp) / (
            self._l * (4.0 / 3.0 - self._mp * costh ** 2 / total_m))
        x_acc = temp - pml * th_acc * costh / total_m
        x = x + self._dt * x_dot
        x_dot = x_dot + self._dt * x_acc
        th = th + self._dt * th_dot
        th_dot = th_dot + self._dt * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        terminated = bool(abs(x) > self._x_lim or abs(th) > self._theta_lim)
        truncated = self._t >= self._max_steps
        return (self._state.astype(np.float32).copy(), 1.0, terminated,
                truncated, {})


class PendulumEnv(Env):
    """Pendulum-v1 (classic control; no gym dependency): continuous torque
    in [-2, 2], obs (cos th, sin th, th_dot), reward
    -(th^2 + 0.1 th_dot^2 + 0.001 a^2); 200-step episodes."""

    observation_dim = 3
    num_actions = 0
    continuous = True
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, max_steps: int = 200):
        self._rng = np.random.RandomState()
        self._max_steps = max_steps
        self._g = 10.0
        self._m = 1.0
        self._l = 1.0
        self._dt = 0.05
        self._state = None
        self._t = 0

    def _obs(self):
        th, th_dot = self._state
        return np.array([np.cos(th), np.sin(th), th_dot], np.float32)

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = np.array([self._rng.uniform(-np.pi, np.pi),
                                self._rng.uniform(-1.0, 1.0)])
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        th, th_dot = self._state
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * th_dot ** 2 + 0.001 * u ** 2
        th_dot = th_dot + (3 * self._g / (2 * self._l) * np.sin(th)
                           + 3.0 / (self._m * self._l ** 2) * u) * self._dt
        th_dot = np.clip(th_dot, -8.0, 8.0)
        th = th + th_dot * self._dt
        self._state = np.array([th, th_dot])
        self._t += 1
        return self._obs(), -float(cost), False, self._t >= self._max_steps, {}


class StatelessCartPole(CartPoleEnv):
    """CartPole with the velocity components hidden (obs = [x, theta]) —
    the standard recurrent-model benchmark (reference:
    rllib/examples/envs/classes/stateless_cartpole.py): only a policy with
    memory can estimate the derivatives it needs to balance."""

    observation_dim = 2

    def _mask(self, obs):
        return obs[[0, 2]].astype(np.float32)

    def reset(self, seed: Optional[int] = None):
        obs, info = super().reset(seed)
        return self._mask(obs), info

    def step(self, action):
        obs, r, term, trunc, info = super().step(action)
        return self._mask(obs), r, term, trunc, info


class MemoryCueEnv(Env):
    """Cue-recall memory task: a one-hot cue is visible ONLY at t=0; after
    `delay` blank steps the agent must emit the matching action. Expected
    reward is 1/num_cues for any memoryless policy and 1.0 for a recurrent
    one — a fast, discriminating LSTM test (the T-maze/recall family the
    reference exercises with its RepeatAfterMeEnv example env)."""

    def __init__(self, num_cues: int = 2, delay: int = 3):
        self._n = num_cues
        self._delay = delay
        self.observation_dim = num_cues + 2  # cue one-hot, cue-phase, t/T
        self.num_actions = num_cues
        self._rng = np.random.RandomState()
        self._cue = 0
        self._t = 0

    def _obs(self):
        o = np.zeros(self.observation_dim, np.float32)
        if self._t == 0:
            o[self._cue] = 1.0
            o[self._n] = 1.0
        o[self._n + 1] = self._t / (self._delay + 1)
        return o

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._cue = int(self._rng.randint(self._n))
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        last = self._t == self._delay
        reward = float(int(action) == self._cue) if last else 0.0
        self._t += 1
        return self._obs(), reward, last, False, {}


class GridGoalEnv(Env):
    """Image-observation navigation: an agent (pixel=1.0) moves on an
    n x n grid toward a fixed goal (pixel=0.5). Exercises the catalog's
    CNN torso end-to-end (the vision-net slot of the reference catalog,
    rllib/models/torch/visionnet.py) without any game dependency."""

    def __init__(self, size: int = 5, max_steps: int = 24):
        self._size = size
        self._max_steps = max_steps
        self.observation_shape = (size, size, 1)
        self.observation_dim = size * size
        self.num_actions = 4  # up, down, left, right
        self._rng = np.random.RandomState()
        self._pos = (0, 0)
        self._goal = (size - 1, size - 1)
        self._t = 0

    def _obs(self):
        o = np.zeros(self.observation_shape, np.float32)
        o[self._goal[0], self._goal[1], 0] = 0.5
        o[self._pos[0], self._pos[1], 0] = 1.0
        return o

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        while True:
            self._pos = (int(self._rng.randint(self._size)),
                         int(self._rng.randint(self._size)))
            if self._pos != self._goal:
                break
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        dr, dc = ((-1, 0), (1, 0), (0, -1), (0, 1))[int(action)]
        r = min(max(self._pos[0] + dr, 0), self._size - 1)
        c = min(max(self._pos[1] + dc, 0), self._size - 1)
        self._pos = (r, c)
        self._t += 1
        at_goal = self._pos == self._goal
        reward = 1.0 if at_goal else -0.02
        return (self._obs(), reward, at_goal,
                self._t >= self._max_steps, {})


class MultiAgentEnv:
    """Multi-agent interface (reference: rllib/env/multi_agent_env.py):
    dict-keyed observations/actions/rewards per agent id. Agents may
    finish at different times; a terminated/truncated agent stops
    appearing in later observation dicts. The special "__all__" key
    signals episode end."""

    agents: List[str]
    observation_dim: int      # per-agent (uniform)
    num_actions: int          # per-agent (uniform)

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class MultiCartPole(MultiAgentEnv):
    """N independent CartPoles with distinct agent ids — the standard
    smoke-test topology for multi-agent sampling (each agent's stream must
    reach its mapped policy with correct credit)."""

    def __init__(self, num_agents: int = 2, max_steps: int = 200):
        self.agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {a: CartPoleEnv(max_steps=max_steps)
                      for a in self.agents}
        self._done: Dict[str, bool] = {}
        self.observation_dim = 4
        self.num_actions = 2

    def reset(self, seed: Optional[int] = None):
        self._done = {a: False for a in self.agents}
        obs = {}
        for i, (a, e) in enumerate(self._envs.items()):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs[a] = o
        return obs, {}

    def step(self, action_dict: Dict[str, Any]):
        # A finished agent's FINAL obs stays in the dict (flagged done) so
        # samplers can bootstrap truncated episodes; it simply stops
        # appearing in subsequent steps (reference: multi_agent_env.py
        # returns last observations alongside the done flags).
        obs, rewards, terms, truncs = {}, {}, {}, {}
        for a, act in action_dict.items():
            if self._done[a]:
                continue
            o, r, te, tr, _ = self._envs[a].step(act)
            obs[a], rewards[a] = o, r
            terms[a], truncs[a] = te, tr
            if te or tr:
                self._done[a] = True
        all_done = all(self._done.values())
        terms["__all__"] = all_done
        truncs["__all__"] = all_done
        return obs, rewards, terms, truncs, {}


_ENV_REGISTRY: Dict[str, Callable[[dict], Env]] = {
    "CartPole-v1": lambda cfg: CartPoleEnv(**cfg),
    "Pendulum-v1": lambda cfg: PendulumEnv(**cfg),
    "MultiCartPole": lambda cfg: MultiCartPole(**cfg),
    "StatelessCartPole": lambda cfg: StatelessCartPole(**cfg),
    "MemoryCue": lambda cfg: MemoryCueEnv(**cfg),
    "GridGoal": lambda cfg: GridGoalEnv(**cfg),
}


def register_env(name: str, creator: Callable[[dict], Env]):
    """tune.register_env equivalent (reference: rllib env registry)."""
    _ENV_REGISTRY[name] = creator


def get_env_creator(spec) -> Callable[[dict], Env]:
    """Resolve a spec to its creator callable ON THE DRIVER, so the callable
    (not a registry name) ships to EnvRunner actors — worker processes have
    their own empty registry."""
    if isinstance(spec, str):
        if spec not in _ENV_REGISTRY:
            raise ValueError(f"unknown env {spec!r}; "
                             f"register_env() it first")
        return _ENV_REGISTRY[spec]
    if callable(spec):
        return spec
    raise TypeError(f"env spec must be str or callable, got {type(spec)}")


def make_env(spec, config: Optional[dict] = None) -> Env:
    return get_env_creator(spec)(config or {})


class EnvSpec:
    def __init__(self, spec, config: Optional[dict] = None):
        self.spec = spec
        self.config = config or {}

    def make(self) -> Env:
        return make_env(self.spec, self.config)
