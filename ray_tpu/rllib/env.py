"""Environments: gym-style API + a dependency-free CartPole.

Reference parity: rllib/env/ (EnvRunner-compatible envs). The registry
mirrors rllib's tune.register_env; CartPole-v1 dynamics follow the classic
control formulation so learning curves are comparable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal gym-style interface: reset() -> (obs, info);
    step(a) -> (obs, reward, terminated, truncated, info)."""

    observation_dim: int
    num_actions: int

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError


class CartPoleEnv(Env):
    """CartPole-v1 (no gym dependency; same constants/termination)."""

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 500):
        self._rng = np.random.RandomState()
        self._max_steps = max_steps
        self._g = 9.8
        self._mc = 1.0
        self._mp = 0.1
        self._l = 0.5
        self._force = 10.0
        self._dt = 0.02
        self._theta_lim = 12 * 2 * np.pi / 360
        self._x_lim = 2.4
        self._state = None
        self._t = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32).copy(), {}

    def step(self, action):
        x, x_dot, th, th_dot = self._state
        force = self._force if action == 1 else -self._force
        costh, sinth = np.cos(th), np.sin(th)
        total_m = self._mc + self._mp
        pml = self._mp * self._l
        temp = (force + pml * th_dot ** 2 * sinth) / total_m
        th_acc = (self._g * sinth - costh * temp) / (
            self._l * (4.0 / 3.0 - self._mp * costh ** 2 / total_m))
        x_acc = temp - pml * th_acc * costh / total_m
        x = x + self._dt * x_dot
        x_dot = x_dot + self._dt * x_acc
        th = th + self._dt * th_dot
        th_dot = th_dot + self._dt * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        terminated = bool(abs(x) > self._x_lim or abs(th) > self._theta_lim)
        truncated = self._t >= self._max_steps
        return (self._state.astype(np.float32).copy(), 1.0, terminated,
                truncated, {})


_ENV_REGISTRY: Dict[str, Callable[[dict], Env]] = {
    "CartPole-v1": lambda cfg: CartPoleEnv(**cfg),
}


def register_env(name: str, creator: Callable[[dict], Env]):
    """tune.register_env equivalent (reference: rllib env registry)."""
    _ENV_REGISTRY[name] = creator


def get_env_creator(spec) -> Callable[[dict], Env]:
    """Resolve a spec to its creator callable ON THE DRIVER, so the callable
    (not a registry name) ships to EnvRunner actors — worker processes have
    their own empty registry."""
    if isinstance(spec, str):
        if spec not in _ENV_REGISTRY:
            raise ValueError(f"unknown env {spec!r}; "
                             f"register_env() it first")
        return _ENV_REGISTRY[spec]
    if callable(spec):
        return spec
    raise TypeError(f"env spec must be str or callable, got {type(spec)}")


def make_env(spec, config: Optional[dict] = None) -> Env:
    return get_env_creator(spec)(config or {})


class EnvSpec:
    def __init__(self, spec, config: Optional[dict] = None):
        self.spec = spec
        self.config = config or {}

    def make(self) -> Env:
        return make_env(self.spec, self.config)
