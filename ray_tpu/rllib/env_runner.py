"""EnvRunner: actor that steps environments with the current policy.

Reference parity: rllib/env/env_runner.py:15 + evaluation/rollout_worker.py
:159. Runs on CPU actors; the policy forward is a small jitted JAX function
on the host. Weights are broadcast from the learner via set_weights (a
plasma object, zero-copy to all runners on one node).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import policy_value_apply, policy_value_init
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


class EnvRunner:
    def __init__(self, env_spec, env_config: dict, num_envs: int,
                 seed: int, hidden=(64, 64), obs_connectors=None,
                 model=None):
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.rllib.connectors import default_obs_pipeline
        self._env_spec = env_spec
        self._env_config = dict(env_config or {})
        self._envs = [make_env(env_spec, env_config) for _ in range(num_envs)]
        self._obs = []
        self._ep_rewards = [0.0] * num_envs
        self._done_rewards: List[float] = []
        for i, e in enumerate(self._envs):
            obs, _ = e.reset(seed=seed + i)
            self._obs.append(obs)
        self._rng = np.random.RandomState(seed)
        # env->module connector pipeline: every obs batch goes through it
        # before the policy forward AND before storage, so the learner
        # trains in the same (preprocessed) observation space.
        self._obs_conn = default_obs_pipeline(obs_connectors)
        self._recurrent = False
        self._build_policy(seed, hidden, model)

    def _build_policy(self, seed: int, hidden, model):
        """Construct self._params + the jitted forward. Subclasses with a
        different head (e.g. C51's distributional Q) override JUST this."""
        import jax
        e0 = self._envs[0]
        obs_dim = e0.observation_dim
        n_act = e0.num_actions
        if model is not None:
            # Catalog path (reference: ModelCatalog.get_model_v2): obs
            # shape drives CNN-vs-MLP; use_lstm threads a carry through
            # sampling (state rows reset on episode end).
            from ray_tpu.rllib.catalog import (ModelConfig, catalog_apply,
                                               catalog_apply_step,
                                               catalog_init, initial_state,
                                               obs_shape_of)
            self._mcfg = ModelConfig.from_dict(model)
            self._params = catalog_init(jax.random.PRNGKey(seed),
                                        obs_shape_of(e0), n_act,
                                        self._mcfg)
            self._recurrent = self._mcfg.use_lstm
            if self._recurrent:
                h, c = initial_state(len(self._envs), self._mcfg)
                self._state = [np.asarray(h), np.asarray(c)]
                mcfg = self._mcfg
                self._jit_step = jax.jit(
                    lambda p, o, s: catalog_apply_step(p, o, s, mcfg))
            else:
                mcfg = self._mcfg
                self._jit_forward = jax.jit(
                    lambda p, o: catalog_apply(p, o, mcfg))
        else:
            self._params = policy_value_init(
                jax.random.PRNGKey(seed), obs_dim, hidden=tuple(hidden),
                num_actions=n_act)
            self._jit_forward = jax.jit(policy_value_apply)

    def set_weights(self, params):
        self._params = params

    def sample(self, num_steps: int, gamma: float = 0.99,
               lam: float = 0.95) -> SampleBatch:
        """Collect num_steps per env; returns a postprocessed batch with
        GAE advantages."""
        if self._recurrent:
            return self._sample_recurrent(num_steps, gamma, lam)
        import jax.nn
        n_envs = len(self._envs)
        cols = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.TERMINATEDS,
                sb.TRUNCATEDS, sb.LOGPS, sb.VF_PREDS, sb.BOOTSTRAP_VALUES)
        per_env: List[Dict[str, List]] = [
            {k: [] for k in cols} for _ in range(n_envs)]
        for _t in range(num_steps):
            obs_arr = self._obs_conn(np.stack(self._obs))
            logits, values = self._jit_forward(self._params, obs_arr)
            logits = np.asarray(logits)
            values = np.asarray(values)
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            for i, env in enumerate(self._envs):
                a = self._rng.choice(len(probs[i]), p=probs[i])
                logp = np.log(probs[i][a] + 1e-10)
                obs2, r, term, trunc, _ = env.step(a)
                rec = per_env[i]
                rec[sb.OBS].append(obs_arr[i])
                rec[sb.ACTIONS].append(a)
                rec[sb.REWARDS].append(r)
                rec[sb.TERMINATEDS].append(term)
                rec[sb.TRUNCATEDS].append(trunc)
                rec[sb.LOGPS].append(logp)
                rec[sb.VF_PREDS].append(values[i])
                # Truncated (not terminated) steps bootstrap from V of the
                # next obs BEFORE the reset wipes it.
                boot = 0.0
                if trunc and not term:
                    nxt = self._obs_conn(obs2[None, :], update=False)
                    _lg, bv = self._jit_forward(self._params, nxt)
                    boot = float(np.asarray(bv)[0])
                rec[sb.BOOTSTRAP_VALUES].append(boot)
                self._ep_rewards[i] += r
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                self._obs[i] = obs2
        batches = []
        obs_arr = self._obs_conn(np.stack(self._obs), update=False)
        _, last_values = self._jit_forward(self._params, obs_arr)
        last_values = np.asarray(last_values)
        for i in range(n_envs):
            b = SampleBatch({k: np.asarray(v) for k, v in per_env[i].items()})
            last_v = 0.0 if b[sb.TERMINATEDS][-1] else float(last_values[i])
            batches.append(compute_gae(b, last_v, gamma, lam))
        return sb.concat_samples(batches)

    def _sample_recurrent(self, num_steps: int, gamma: float,
                          lam: float) -> SampleBatch:
        """Recurrent rollout: per-env (h, c) carry threads across
        fragments; rows reset to zero on episode end. Each env's T steps
        form one contiguous training sequence, with per-step done_prev and
        state_in columns so the learner's scan replays the exact carries
        (reference: recurrent sampling in rollout_worker + the
        max_seq_len trajectory-view machinery)."""
        n_envs = len(self._envs)
        cols = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.TERMINATEDS,
                sb.TRUNCATEDS, sb.LOGPS, sb.VF_PREDS, sb.BOOTSTRAP_VALUES,
                sb.DONE_PREV, sb.STATE_IN_H, sb.STATE_IN_C)
        per_env: List[Dict[str, List]] = [
            {k: [] for k in cols} for _ in range(n_envs)]
        done_prev = np.zeros(n_envs, np.float32)
        for _t in range(num_steps):
            obs_arr = self._obs_conn(np.stack(self._obs))
            h_in, c_in = self._state
            logits, values, (h2, c2) = self._jit_step(
                self._params, obs_arr, (h_in, c_in))
            logits = np.asarray(logits)
            values = np.asarray(values)
            h2, c2 = np.array(h2), np.array(c2)
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            for i, env in enumerate(self._envs):
                a = self._rng.choice(len(probs[i]), p=probs[i])
                obs2, r, term, trunc, _ = env.step(a)
                rec = per_env[i]
                rec[sb.OBS].append(obs_arr[i])
                rec[sb.ACTIONS].append(a)
                rec[sb.REWARDS].append(r)
                rec[sb.TERMINATEDS].append(term)
                rec[sb.TRUNCATEDS].append(trunc)
                rec[sb.LOGPS].append(np.log(probs[i][a] + 1e-10))
                rec[sb.VF_PREDS].append(values[i])
                rec[sb.DONE_PREV].append(done_prev[i])
                # Per-step carry rows (the learner reads only each
                # sequence's first row): SampleBatch columns must be
                # equal-length, and cell-size rows are small next to obs.
                rec[sb.STATE_IN_H].append(h_in[i])
                rec[sb.STATE_IN_C].append(c_in[i])
                boot = 0.0
                if trunc and not term:
                    nxt = self._obs_conn(obs2[None], update=False)
                    _lg, bv, _st = self._jit_step(
                        self._params, nxt, (h2[i:i + 1], c2[i:i + 1]))
                    boot = float(np.asarray(bv)[0])
                rec[sb.BOOTSTRAP_VALUES].append(boot)
                self._ep_rewards[i] += r
                done_prev[i] = 0.0
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                    h2[i] = 0.0
                    c2[i] = 0.0
                    done_prev[i] = 1.0
                self._obs[i] = obs2
            self._state = [h2, c2]
        obs_arr = self._obs_conn(np.stack(self._obs), update=False)
        _lg, last_values, _st = self._jit_step(
            self._params, obs_arr, tuple(self._state))
        last_values = np.asarray(last_values)
        batches = []
        for i in range(n_envs):
            b = SampleBatch({k: np.asarray(v) for k, v in per_env[i].items()})
            last_v = 0.0 if b[sb.TERMINATEDS][-1] else float(last_values[i])
            batches.append(compute_gae(b, last_v, gamma, lam))
        return sb.concat_samples(batches)

    def sample_transitions(self, num_steps: int,
                           epsilon: float = 0.0) -> SampleBatch:
        """(obs, action, reward, next_obs, done) tuples with epsilon-greedy
        over the policy head's scores — the value-based (DQN-family)
        collection mode (reference: RolloutWorker with
        EpsilonGreedy exploration)."""
        assert not self._recurrent, (
            "DQN-family transition sampling does not support use_lstm "
            "(the reference gates this behind R2D2)")
        cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.NEXT_OBS, sb.TERMINATEDS,
                                sb.TRUNCATEDS)}
        for _t in range(num_steps):
            obs_arr = self._obs_conn(np.stack(self._obs))
            scores, _ = self._jit_forward(self._params, obs_arr)
            scores = np.asarray(scores)
            for i, env in enumerate(self._envs):
                if self._rng.rand() < epsilon:
                    a = self._rng.randint(scores.shape[-1])
                else:
                    a = int(np.argmax(scores[i]))
                obs2, r, term, trunc, _ = env.step(a)
                cols[sb.OBS].append(obs_arr[i])
                cols[sb.ACTIONS].append(a)
                cols[sb.REWARDS].append(r)
                cols[sb.NEXT_OBS].append(
                    self._obs_conn(obs2[None, :], update=False)[0])
                cols[sb.TERMINATEDS].append(term)
                cols[sb.TRUNCATEDS].append(trunc)
                self._ep_rewards[i] += r
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                self._obs[i] = obs2
        return SampleBatch({k: np.asarray(v) for k, v in cols.items()})

    def evaluate_return(self, params, episodes: int = 1,
                        max_steps: int = 500) -> float:
        """Mean greedy-episode return under `params` on a FRESH env (the
        evaluation-worker primitive; also the ES/ARS fitness fn)."""
        env = make_env(self._env_spec, self._env_config)
        total = 0.0
        for _ep in range(episodes):
            obs, _ = env.reset(seed=int(self._rng.randint(2 ** 31)))
            state = None
            if self._recurrent:
                from ray_tpu.rllib.catalog import initial_state
                state = initial_state(1, self._mcfg)
            for _ in range(max_steps):
                x = self._obs_conn(np.asarray(obs)[None], update=False)
                if self._recurrent:
                    logits, _v, state = self._jit_step(params, x, state)
                else:
                    logits, _v = self._jit_forward(params, x)
                obs, r, term, trunc, _ = env.step(
                    int(np.argmax(np.asarray(logits)[0])))
                total += r
                if term or trunc:
                    break
        return total / episodes

    def evaluate_perturbations(self, flat_params, seeds: List[int],
                               sigma: float, episodes: int = 1,
                               max_steps: int = 500):
        """Antithetic ES/ARS evaluations: each seed's noise vector is
        REBUILT from the seed (no noise shipping — the reference's
        shared-noise-table trick, rllib/algorithms/es) and scored as
        (R(theta + sigma*eps), R(theta - sigma*eps))."""
        from jax.flatten_util import ravel_pytree
        _flat0, unravel = ravel_pytree(self._params)
        flat = np.asarray(flat_params, np.float32)
        out = []
        for seed in seeds:
            eps = np.random.RandomState(seed).standard_normal(
                flat.shape).astype(np.float32)
            r_pos = self.evaluate_return(
                unravel(flat + sigma * eps), episodes, max_steps)
            r_neg = self.evaluate_return(
                unravel(flat - sigma * eps), episodes, max_steps)
            out.append((r_pos, r_neg))
        return out

    def get_flat_params(self):
        from jax.flatten_util import ravel_pytree
        flat, _ = ravel_pytree(self._params)
        return np.asarray(flat, np.float32)


    def episode_rewards(self, clear: bool = True) -> List[float]:
        out = list(self._done_rewards)
        if clear:
            self._done_rewards.clear()
        return out

    def ping(self):
        return True


class _RewardTracker:
    """Shared episode-reward bookkeeping for all runner flavors."""

    def _init_rewards(self):
        self._done_rewards: List[float] = []

    def episode_rewards(self, clear: bool = True) -> List[float]:
        out = list(self._done_rewards)
        if clear:
            self._done_rewards.clear()
        return out

    def ping(self):
        return True


class ContinuousEnvRunner(_RewardTracker):
    """Rollout actor for continuous-control (SAC family): actions sampled
    from the tanh-squashed Gaussian actor; emits transition batches
    (reference: rollout_worker.py with StochasticSampling exploration)."""

    def __init__(self, env_spec, env_config: dict, num_envs: int,
                 seed: int, hidden=(64, 64), policy: str = "squashed_gaussian",
                 expl_noise: float = 0.1, obs_connectors=None,
                 action_connectors=None):
        import jax
        import jax.numpy as jnp
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.rllib.connectors import (default_action_pipeline,
                                              default_obs_pipeline)
        from ray_tpu.rllib.models import (det_actor_apply, det_actor_init,
                                          squashed_gaussian_init,
                                          squashed_gaussian_sample)
        self._envs = [make_env(env_spec, env_config) for _ in range(num_envs)]
        e0 = self._envs[0]
        assert e0.continuous, "ContinuousEnvRunner needs a continuous env"
        self._low, self._high = e0.action_low, e0.action_high
        self._obs_conn = default_obs_pipeline(obs_connectors)
        self._act_conn = default_action_pipeline(self._low, self._high,
                                                 action_connectors)
        self._seed = seed
        self._obs = []
        self._ep_rewards = [0.0] * num_envs
        self._init_rewards()
        for i, e in enumerate(self._envs):
            obs, _ = e.reset(seed=seed + i)
            self._obs.append(obs)
        self._key = jax.random.PRNGKey(seed)
        if policy == "deterministic":
            # DDPG/TD3 exploration: mu(s) + N(0, expl_noise*scale), clipped
            # (reference: rllib/algorithms/ddpg GaussianNoise exploration).
            self._params = det_actor_init(self._key, e0.observation_dim,
                                          e0.action_dim, hidden=tuple(hidden))
            sigma = expl_noise * (self._high - self._low) / 2.0

            def det_sample(k, p, o):
                a = det_actor_apply(p, o, self._low, self._high)
                a = a + sigma * jax.random.normal(k, a.shape)
                return jnp.clip(a, self._low, self._high), None

            self._jit_sample = jax.jit(det_sample)
        else:
            self._params = squashed_gaussian_init(
                self._key, e0.observation_dim, e0.action_dim,
                hidden=tuple(hidden))
            self._jit_sample = jax.jit(
                lambda k, p, o: squashed_gaussian_sample(
                    k, p, o, self._low, self._high))

    def set_weights(self, params):
        self._params = params

    def sample_transitions(self, num_steps: int,
                           random_until: int = 0,
                           steps_done: int = 0) -> SampleBatch:
        """(obs, action, reward, next_obs, done) transitions. The first
        `random_until` total env steps act uniformly at random (SAC warmup
        exploration; reference: sac.py num_steps_sampled_before_learning).
        The warmup RNG mixes the runner seed so parallel runners explore
        independently."""
        import jax
        cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.NEXT_OBS, sb.TERMINATEDS)}
        rng = np.random.RandomState(
            (self._seed * 9973 + steps_done + 1) % (2 ** 31))
        for t in range(num_steps):
            obs_arr = self._obs_conn(np.stack(self._obs))
            if steps_done + t < random_until:
                acts = rng.uniform(self._low, self._high,
                                   size=(len(self._envs),
                                         self._envs[0].action_dim))
            else:
                self._key, sub = jax.random.split(self._key)
                acts, _ = self._jit_sample(sub, self._params, obs_arr)
                acts = np.asarray(acts)
            acts = self._act_conn(acts)
            for i, env in enumerate(self._envs):
                obs2, r, term, trunc, _ = env.step(acts[i])
                cols[sb.OBS].append(obs_arr[i])
                cols[sb.ACTIONS].append(acts[i])
                cols[sb.REWARDS].append(r)
                cols[sb.NEXT_OBS].append(
                    self._obs_conn(obs2[None, :], update=False)[0])
                cols[sb.TERMINATEDS].append(term)
                self._ep_rewards[i] += r
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                self._obs[i] = obs2
        return SampleBatch({k: np.asarray(v) for k, v in cols.items()})


class MultiAgentEnvRunner(_RewardTracker):
    """Multi-agent sampling: per-agent episode streams routed to policies
    via policy_mapping_fn, GAE per completed trajectory, one
    MultiAgentBatch out (reference: rllib/env/multi_agent_env.py +
    evaluation/rollout_worker.py:159 multi-policy sampling).

    Vectorized over num_envs env copies; trajectories are keyed
    (env index, agent id) so parallel episodes never mix."""

    _COLS = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.TERMINATEDS, sb.TRUNCATEDS,
             sb.LOGPS, sb.VF_PREDS, sb.BOOTSTRAP_VALUES)

    def __init__(self, env_spec, env_config: dict, policies: List[str],
                 policy_mapping_fn, num_envs: int = 1, seed: int = 0,
                 hidden=(64, 64)):
        import jax
        jax.config.update("jax_platforms", "cpu")
        self._envs = [make_env(env_spec, env_config)
                      for _ in range(num_envs)]
        self._mapping = policy_mapping_fn
        self._rng = np.random.RandomState(seed)
        e0 = self._envs[0]
        self._params = {
            pid: policy_value_init(jax.random.PRNGKey(seed + j),
                                   e0.observation_dim,
                                   hidden=tuple(hidden),
                                   num_actions=e0.num_actions)
            for j, pid in enumerate(policies)
        }
        self._jit_forward = jax.jit(policy_value_apply)
        self._obs: List[Dict[str, Any]] = []
        for i, e in enumerate(self._envs):
            obs, _ = e.reset(seed=seed + i)
            self._obs.append(obs)
        self._ep_rewards: Dict[tuple, float] = {}
        self._init_rewards()
        # (env idx, agent id) -> in-progress trajectory columns
        self._traj: Dict[tuple, Dict[str, list]] = {}

    def set_weights(self, params: Dict[str, Any]):
        self._params.update(params)

    def _forward(self, pid: str, obs_batch: np.ndarray):
        lg, vl = self._jit_forward(self._params[pid], obs_batch)
        return np.asarray(lg), np.asarray(vl)

    def _finish_traj(self, key: tuple, out: Dict[str, list],
                     last_value: float, gamma: float, lam: float):
        cols = self._traj.pop(key, None)
        if not cols or not cols[sb.OBS]:
            return
        b = SampleBatch({k: np.asarray(v) for k, v in cols.items()})
        pid = self._mapping(key[1])
        out.setdefault(pid, []).append(
            compute_gae(b, last_value, gamma, lam))

    def sample(self, num_steps: int, gamma: float = 0.99,
               lam: float = 0.95):
        """Collect num_steps steps PER ENV; returns MultiAgentBatch keyed
        by policy id."""
        from ray_tpu.rllib.sample_batch import MultiAgentBatch
        done_batches: Dict[str, list] = {}
        for _t in range(num_steps):
            # Gather live (env, agent) pairs across all env copies.
            pairs = []
            for i in range(len(self._envs)):
                if not self._obs[i]:  # every agent finished: new episode
                    self._obs[i], _ = self._envs[i].reset()
                pairs.extend((i, a) for a in self._obs[i])
            obs_arr = np.stack([self._obs[i][a] for i, a in pairs])
            n_act = self._envs[0].num_actions
            logits = np.zeros((len(pairs), n_act), np.float32)
            values = np.zeros((len(pairs),), np.float32)
            by_pid: Dict[str, list] = {}
            for idx, (i, a) in enumerate(pairs):
                by_pid.setdefault(self._mapping(a), []).append(idx)
            for pid, idxs in by_pid.items():
                lg, vl = self._forward(pid, obs_arr[idxs])
                logits[idxs] = lg
                values[idxs] = vl
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            actions = [
                int(self._rng.choice(n_act, p=probs[idx]))
                for idx in range(len(pairs))
            ]
            # Step each env with its agents' actions.
            stepped = []
            for i, env in enumerate(self._envs):
                acts = {a: actions[idx]
                        for idx, (j, a) in enumerate(pairs) if j == i}
                if acts:
                    stepped.append((i, *env.step(acts)))
            results = {i: (obs2, rew, te, tr)
                       for i, obs2, rew, te, tr, _ in stepped}
            for idx, (i, a) in enumerate(pairs):
                obs2, rewards, terms, truncs = results[i]
                term = bool(terms.get(a, False))
                trunc = bool(truncs.get(a, False))
                rec = self._traj.setdefault(
                    (i, a), {k: [] for k in self._COLS})
                rec[sb.OBS].append(self._obs[i][a])
                rec[sb.ACTIONS].append(actions[idx])
                rec[sb.REWARDS].append(rewards.get(a, 0.0))
                rec[sb.TERMINATEDS].append(term)
                rec[sb.TRUNCATEDS].append(trunc)
                rec[sb.LOGPS].append(
                    np.log(probs[idx][actions[idx]] + 1e-10))
                rec[sb.VF_PREDS].append(values[idx])
                boot = 0.0
                if trunc and not term and a in obs2:
                    _lg, bv = self._forward(self._mapping(a),
                                            obs2[a][None, :])
                    boot = float(bv[0])
                rec[sb.BOOTSTRAP_VALUES].append(boot)
                k = (i, a)
                self._ep_rewards[k] = (self._ep_rewards.get(k, 0.0)
                                       + rewards.get(a, 0.0))
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards.pop(k, 0.0))
                    self._finish_traj(k, done_batches, 0.0, gamma, lam)
            # Done agents leave the tracked obs (their final obs was only
            # needed for the truncation bootstrap above).
            for i, *_rest in stepped:
                obs2, rewards, terms, truncs = results[i]
                self._obs[i] = {
                    a: o for a, o in obs2.items()
                    if not (terms.get(a, False) or truncs.get(a, False))}
        # Rollout boundary: close out in-progress trajectories with a
        # bootstrap value from the current obs.
        for (i, a) in list(self._traj.keys()):
            last_v = 0.0
            if a in self._obs[i]:
                _lg, bv = self._forward(self._mapping(a),
                                        self._obs[i][a][None, :])
                last_v = float(bv[0])
            self._finish_traj((i, a), done_batches, last_v, gamma, lam)
        return MultiAgentBatch(
            {pid: sb.concat_samples(bs)
             for pid, bs in done_batches.items()},
            num_steps * len(self._envs))
