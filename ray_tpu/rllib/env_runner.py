"""EnvRunner: actor that steps environments with the current policy.

Reference parity: rllib/env/env_runner.py:15 + evaluation/rollout_worker.py
:159. Runs on CPU actors; the policy forward is a small jitted JAX function
on the host. Weights are broadcast from the learner via set_weights (a
plasma object, zero-copy to all runners on one node).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import policy_value_apply, policy_value_init
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch, compute_gae


class EnvRunner:
    def __init__(self, env_spec, env_config: dict, num_envs: int,
                 seed: int, hidden=(64, 64)):
        import jax
        jax.config.update("jax_platforms", "cpu")
        self._envs = [make_env(env_spec, env_config) for _ in range(num_envs)]
        self._obs = []
        self._ep_rewards = [0.0] * num_envs
        self._done_rewards: List[float] = []
        for i, e in enumerate(self._envs):
            obs, _ = e.reset(seed=seed + i)
            self._obs.append(obs)
        self._rng = np.random.RandomState(seed)
        obs_dim = self._envs[0].observation_dim
        n_act = self._envs[0].num_actions
        self._params = policy_value_init(jax.random.PRNGKey(seed), obs_dim,
                                         hidden=tuple(hidden),
                                         num_actions=n_act)
        self._jit_forward = jax.jit(policy_value_apply)

    def set_weights(self, params):
        self._params = params

    def sample(self, num_steps: int, gamma: float = 0.99,
               lam: float = 0.95) -> SampleBatch:
        """Collect num_steps per env; returns a postprocessed batch with
        GAE advantages."""
        import jax.nn
        n_envs = len(self._envs)
        cols = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.TERMINATEDS,
                sb.TRUNCATEDS, sb.LOGPS, sb.VF_PREDS, sb.BOOTSTRAP_VALUES)
        per_env: List[Dict[str, List]] = [
            {k: [] for k in cols} for _ in range(n_envs)]
        for _t in range(num_steps):
            obs_arr = np.stack(self._obs)
            logits, values = self._jit_forward(self._params, obs_arr)
            logits = np.asarray(logits)
            values = np.asarray(values)
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            for i, env in enumerate(self._envs):
                a = self._rng.choice(len(probs[i]), p=probs[i])
                logp = np.log(probs[i][a] + 1e-10)
                obs2, r, term, trunc, _ = env.step(a)
                rec = per_env[i]
                rec[sb.OBS].append(self._obs[i])
                rec[sb.ACTIONS].append(a)
                rec[sb.REWARDS].append(r)
                rec[sb.TERMINATEDS].append(term)
                rec[sb.TRUNCATEDS].append(trunc)
                rec[sb.LOGPS].append(logp)
                rec[sb.VF_PREDS].append(values[i])
                # Truncated (not terminated) steps bootstrap from V of the
                # next obs BEFORE the reset wipes it.
                boot = 0.0
                if trunc and not term:
                    _lg, bv = self._jit_forward(self._params, obs2[None, :])
                    boot = float(np.asarray(bv)[0])
                rec[sb.BOOTSTRAP_VALUES].append(boot)
                self._ep_rewards[i] += r
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                self._obs[i] = obs2
        batches = []
        obs_arr = np.stack(self._obs)
        _, last_values = self._jit_forward(self._params, obs_arr)
        last_values = np.asarray(last_values)
        for i in range(n_envs):
            b = SampleBatch({k: np.asarray(v) for k, v in per_env[i].items()})
            last_v = 0.0 if b[sb.TERMINATEDS][-1] else float(last_values[i])
            batches.append(compute_gae(b, last_v, gamma, lam))
        return sb.concat_samples(batches)

    def sample_transitions(self, num_steps: int,
                           epsilon: float = 0.0) -> SampleBatch:
        """(obs, action, reward, next_obs, done) tuples with epsilon-greedy
        over the policy head's scores — the value-based (DQN-family)
        collection mode (reference: RolloutWorker with
        EpsilonGreedy exploration)."""
        cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.NEXT_OBS, sb.TERMINATEDS)}
        for _t in range(num_steps):
            obs_arr = np.stack(self._obs)
            scores, _ = self._jit_forward(self._params, obs_arr)
            scores = np.asarray(scores)
            for i, env in enumerate(self._envs):
                if self._rng.rand() < epsilon:
                    a = self._rng.randint(scores.shape[-1])
                else:
                    a = int(np.argmax(scores[i]))
                obs2, r, term, trunc, _ = env.step(a)
                cols[sb.OBS].append(self._obs[i])
                cols[sb.ACTIONS].append(a)
                cols[sb.REWARDS].append(r)
                cols[sb.NEXT_OBS].append(obs2)
                cols[sb.TERMINATEDS].append(term)
                self._ep_rewards[i] += r
                if term or trunc:
                    self._done_rewards.append(self._ep_rewards[i])
                    self._ep_rewards[i] = 0.0
                    obs2, _ = env.reset()
                self._obs[i] = obs2
        return SampleBatch({k: np.asarray(v) for k, v in cols.items()})

    def episode_rewards(self, clear: bool = True) -> List[float]:
        out = list(self._done_rewards)
        if clear:
            self._done_rewards.clear()
        return out

    def ping(self):
        return True
