"""Connectors: composable obs/action transform pipelines shared across
algorithms.

Reference parity: rllib/connectors/ (env-to-module pipelines preprocess
observations before the RLModule forward; module-to-env pipelines
postprocess actions before env.step). Here a ConnectorPipeline is a plain
callable chain living inside each EnvRunner actor:

    obs pipeline    : raw env obs batch  -> policy input batch
    action pipeline : policy output batch -> env action batch

Stateful connectors (NormalizeObs) carry running statistics; pipelines are
cloudpickled into runner actors, so each runner keeps independent state
(same as the reference's per-EnvRunner connector state).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class Connector:
    """One transform step. `update=False` applies the transform without
    advancing internal statistics (used for bootstrap/next-obs passes so
    a sample isn't counted twice)."""

    def __call__(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Optional[Sequence[Connector]] = None):
        self.connectors: List[Connector] = list(connectors or [])

    def __call__(self, x, update: bool = True):
        for c in self.connectors:
            x = c(x, update)
        return x

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def state(self) -> dict:
        return {i: c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


# ---------------------------------------------------------------------------
# env -> module (observation) connectors
# ---------------------------------------------------------------------------

class CastObsF32(Connector):
    """float32-cast + NaN/inf scrub (reference: connectors/env_to_module)."""

    def __call__(self, x, update: bool = True):
        x = np.asarray(x, np.float32)
        return np.nan_to_num(x, posinf=3.4e38, neginf=-3.4e38)


class FlattenObs(Connector):
    """Flatten per-row structure to a 1-D feature vector per sample."""

    def __call__(self, x, update: bool = True):
        x = np.asarray(x)
        return x.reshape(x.shape[0], -1) if x.ndim > 2 else x


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, x, update: bool = True):
        return np.clip(x, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std normalization (Welford), the MeanStdFilter
    equivalent (reference: connectors/env_to_module/mean_std_filter.py)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.eps = eps
        self.clip = clip
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, x, update: bool = True):
        x = np.asarray(x, np.float32)
        batch = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[None, :]
        if self.mean is None:
            self.mean = np.zeros(batch.shape[-1], np.float64)
            self.m2 = np.zeros(batch.shape[-1], np.float64)
        if update and len(batch):
            # Chan parallel-variance merge: one vectorized update per
            # batch instead of a per-row Python loop (hot sampling path).
            n_b = float(len(batch))
            mean_b = batch.mean(axis=0, dtype=np.float64)
            m2_b = ((batch - mean_b) ** 2).sum(axis=0, dtype=np.float64)
            delta = mean_b - self.mean
            total = self.count + n_b
            self.mean += delta * (n_b / total)
            self.m2 += m2_b + delta * delta * (self.count * n_b / total)
            self.count = total
        if self.count < 2:
            return x
        std = np.sqrt(self.m2 / (self.count - 1)) + self.eps
        out = (x - self.mean.astype(np.float32)) / std.astype(np.float32)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state(self) -> dict:
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state: dict) -> None:
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


# ---------------------------------------------------------------------------
# module -> env (action) connectors
# ---------------------------------------------------------------------------

class ClipAction(Connector):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def __call__(self, x, update: bool = True):
        return np.clip(x, self.low, self.high)


class UnsquashAction(Connector):
    """[-1, 1] policy output -> [low, high] env range (reference:
    connectors/module_to_env unsquash_actions)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, x, update: bool = True):
        x = np.clip(np.asarray(x, np.float32), -1.0, 1.0)
        return self.low + (x + 1.0) * 0.5 * (self.high - self.low)


def default_obs_pipeline(extra: Optional[Sequence[Connector]] = None
                         ) -> ConnectorPipeline:
    return ConnectorPipeline([CastObsF32(), *(extra or [])])


def default_action_pipeline(low, high,
                            extra: Optional[Sequence[Connector]] = None
                            ) -> ConnectorPipeline:
    return ConnectorPipeline([*(extra or []), ClipAction(low, high)])
