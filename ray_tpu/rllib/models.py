"""Policy/value networks as pure JAX functions.

Reference parity: rllib/models/ (the default fully-connected nets) +
rllib/core/rl_module/rl_module.py:237 conceptually — a module is
(init_fn, apply_fn) over a params pytree, jit/pmap-able by construction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def mlp_init(rng, sizes: List[int], dtype=None) -> List[Dict[str, Any]]:
    import jax
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.orthogonal(k, max(fan_in, fan_out))[:fan_in, :fan_out]
        w = w * np.sqrt(2.0)
        params.append({"w": jnp.asarray(w, dtype),
                       "b": jnp.zeros((fan_out,), dtype)})
    return params


def mlp_apply(params, x, final_scale: float = 1.0):
    import jax.numpy as jnp
    h = x
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            h = jnp.tanh(h)
    return h * final_scale


def policy_value_init(rng, obs_dim: int, num_actions: int,
                      hidden: Tuple[int, ...] = (64, 64)):
    """Separate policy and value MLPs (rllib default fcnet)."""
    import jax
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, [obs_dim, *hidden, num_actions]),
        "vf": mlp_init(k2, [obs_dim, *hidden, 1]),
    }


def policy_value_apply(params, obs):
    """-> (logits, value)."""
    logits = mlp_apply(params["pi"], obs, final_scale=0.01)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def sample_action(rng, logits):
    """Categorical sample + log-prob."""
    import jax
    import jax.numpy as jnp
    a = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), a]
    return a, logp


# ---- continuous control (SAC family) -----------------------------------

def squashed_gaussian_init(rng, obs_dim: int, action_dim: int,
                           hidden: Tuple[int, ...] = (64, 64)):
    """Actor emitting (mean, log_std) for a tanh-squashed Gaussian
    (reference: rllib/models catalog's SquashedGaussian distribution)."""
    import jax
    k = jax.random.split(rng, 1)[0]
    return {"net": mlp_init(k, [obs_dim, *hidden, 2 * action_dim])}


def squashed_gaussian_apply(params, obs):
    """-> (mean, log_std), log_std clipped to a sane range."""
    import jax.numpy as jnp
    out = mlp_apply(params["net"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, -20.0, 2.0)


def squashed_gaussian_sample(rng, params, obs, low: float, high: float):
    """Reparameterized sample -> (action in [low, high], log_prob)."""
    import jax
    import jax.numpy as jnp
    mean, log_std = squashed_gaussian_apply(params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    tanh = jnp.tanh(pre)
    # log N(pre) - log |d tanh/d pre|, summed over action dims.
    logp = (-0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            - jnp.log(1 - tanh ** 2 + 1e-6)).sum(-1)
    scale = (high - low) / 2.0
    mid = (high + low) / 2.0
    return mid + scale * tanh, logp


def det_actor_init(rng, obs_dim: int, action_dim: int,
                   hidden: Tuple[int, ...] = (64, 64)):
    """Deterministic policy mu(s) for DDPG/TD3 (reference:
    rllib/algorithms/ddpg deterministic actor)."""
    import jax
    k = jax.random.split(rng, 1)[0]
    return {"net": mlp_init(k, [obs_dim, *hidden, action_dim])}


def det_actor_apply(params, obs, low: float, high: float):
    """tanh-bounded deterministic action in [low, high]."""
    import jax.numpy as jnp
    scale = (high - low) / 2.0
    mid = (high + low) / 2.0
    return mid + scale * jnp.tanh(mlp_apply(params["net"], obs))


def twin_q_init(rng, obs_dim: int, action_dim: int,
                hidden: Tuple[int, ...] = (64, 64)):
    """Two independent Q(s, a) critics (clipped double-Q)."""
    import jax
    k1, k2 = jax.random.split(rng)
    sizes = [obs_dim + action_dim, *hidden, 1]
    return {"q1": mlp_init(k1, sizes), "q2": mlp_init(k2, sizes)}


def twin_q_apply(params, obs, action):
    import jax.numpy as jnp
    x = jnp.concatenate([obs, action], axis=-1)
    return (mlp_apply(params["q1"], x)[..., 0],
            mlp_apply(params["q2"], x)[..., 0])
