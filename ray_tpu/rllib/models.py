"""Policy/value networks as pure JAX functions.

Reference parity: rllib/models/ (the default fully-connected nets) +
rllib/core/rl_module/rl_module.py:237 conceptually — a module is
(init_fn, apply_fn) over a params pytree, jit/pmap-able by construction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def mlp_init(rng, sizes: List[int], dtype=None) -> List[Dict[str, Any]]:
    import jax
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.orthogonal(k, max(fan_in, fan_out))[:fan_in, :fan_out]
        w = w * np.sqrt(2.0)
        params.append({"w": jnp.asarray(w, dtype),
                       "b": jnp.zeros((fan_out,), dtype)})
    return params


def mlp_apply(params, x, final_scale: float = 1.0):
    import jax.numpy as jnp
    h = x
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            h = jnp.tanh(h)
    return h * final_scale


def policy_value_init(rng, obs_dim: int, num_actions: int,
                      hidden: Tuple[int, ...] = (64, 64)):
    """Separate policy and value MLPs (rllib default fcnet)."""
    import jax
    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, [obs_dim, *hidden, num_actions]),
        "vf": mlp_init(k2, [obs_dim, *hidden, 1]),
    }


def policy_value_apply(params, obs):
    """-> (logits, value)."""
    logits = mlp_apply(params["pi"], obs, final_scale=0.01)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


def sample_action(rng, logits):
    """Categorical sample + log-prob."""
    import jax
    import jax.numpy as jnp
    a = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), a]
    return a, logp
