"""Model catalog: obs-space-driven network construction.

Reference parity: rllib/models/catalog.py (ModelCatalog.get_model_v2 picks
a default fcnet / vision net / adds an LSTM wrapper from the model config
dict) and rllib/models/torch/{fcnet,visionnet,recurrent_net}.py. Here the
catalog emits pure (init, apply) JAX functions over a params pytree:

  - flat observations  -> MLP torso (tanh, orthogonal init)
  - image observations -> CNN torso (relu, NHWC conv stack) + dense
  - use_lstm=True      -> an LSTM cell between torso and heads; sequence
    training runs the cell under lax.scan with carry resets at episode
    boundaries (done_prev), so one compiled program handles fragments
    containing any number of episode ends — no Python-side unrolling.

Model config keys mirror the reference's (fcnet_hiddens, conv_filters,
use_lstm, lstm_cell_size, vf_share_layers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class ModelConfig:
    """Catalog knobs (subset of the reference MODEL_DEFAULTS that matters
    for the nets we build)."""

    def __init__(self,
                 fcnet_hiddens: Sequence[int] = (64, 64),
                 conv_filters: Optional[Sequence[Tuple[int, int, int]]] = None,
                 use_lstm: bool = False,
                 lstm_cell_size: int = 64,
                 vf_share_layers: bool = False):
        self.fcnet_hiddens = tuple(fcnet_hiddens)
        # [(out_channels, kernel, stride), ...]; None -> auto for the input.
        self.conv_filters = (None if conv_filters is None
                             else [tuple(f) for f in conv_filters])
        self.use_lstm = bool(use_lstm)
        self.lstm_cell_size = int(lstm_cell_size)
        self.vf_share_layers = bool(vf_share_layers)

    _KEYS = ("fcnet_hiddens", "conv_filters", "use_lstm",
             "lstm_cell_size", "vf_share_layers")

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ModelConfig":
        d = dict(d or {})
        unknown = set(d) - set(ModelConfig._KEYS)
        if unknown:
            raise ValueError(
                f"unknown model config keys {sorted(unknown)}; "
                f"supported: {list(ModelConfig._KEYS)}")
        return ModelConfig(**d)

    def to_dict(self) -> Dict[str, Any]:
        return {"fcnet_hiddens": list(self.fcnet_hiddens),
                "conv_filters": self.conv_filters,
                "use_lstm": self.use_lstm,
                "lstm_cell_size": self.lstm_cell_size,
                "vf_share_layers": self.vf_share_layers}


def _default_conv_filters(obs_shape) -> List[Tuple[int, int, int]]:
    """Small-input defaults (the reference ships 84x84 Atari filters; our
    built-in image envs are small grids, so scale to the input)."""
    h = obs_shape[0]
    if h >= 32:
        return [(16, 8, 4), (32, 4, 2), (64, 3, 1)]
    if h >= 10:
        return [(16, 4, 2), (32, 3, 2)]
    return [(16, 3, 1), (32, 3, 1)]


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def _dense_init(rng, fan_in: int, fan_out: int, scale: float = np.sqrt(2.0)):
    import jax
    import jax.numpy as jnp
    w = jax.random.orthogonal(rng, max(fan_in, fan_out))[:fan_in, :fan_out]
    return {"w": jnp.asarray(w * scale, jnp.float32),
            "b": jnp.zeros((fan_out,), jnp.float32)}


def _mlp_init(rng, sizes: List[int]):
    import jax
    keys = jax.random.split(rng, max(len(sizes) - 1, 1))
    return [_dense_init(k, i, o)
            for k, (i, o) in zip(keys, zip(sizes[:-1], sizes[1:]))]


def _conv_init(rng, in_ch: int, out_ch: int, kernel: int):
    import jax
    import jax.numpy as jnp
    fan_in = kernel * kernel * in_ch
    w = jax.random.normal(rng, (kernel, kernel, in_ch, out_ch),
                          jnp.float32) * np.sqrt(2.0 / fan_in)
    return {"w": w, "b": jnp.zeros((out_ch,), jnp.float32)}


def _normalize_obs_shape(obs_shape) -> Tuple[int, ...]:
    shape = tuple(int(s) for s in obs_shape)
    if len(shape) == 2:          # (H, W) grayscale -> (H, W, 1)
        shape = shape + (1,)
    return shape


def _torso_init(rng, obs_shape, cfg: ModelConfig):
    """-> (params, feature_dim). CNN for rank>=2 obs, MLP otherwise.

    Params hold ONLY arrays (jax pytree leaves); the static structure
    (mlp-vs-cnn, strides) is re-derived from (cfg, obs shape) at apply
    time so the same config built runner- and learner-side agrees."""
    import jax
    shape = _normalize_obs_shape(obs_shape)
    if len(shape) == 1:
        sizes = [shape[0], *cfg.fcnet_hiddens]
        return {"layers": _mlp_init(rng, sizes)}, sizes[-1]
    filters = cfg.conv_filters or _default_conv_filters(shape)
    h, w, ch = shape
    keys = jax.random.split(rng, len(filters) + 1)
    convs = []
    for k, (out_ch, kernel, stride) in zip(keys, filters):
        convs.append(_conv_init(k, ch, out_ch, kernel))
        # SAME padding: ceil-div spatial reduction.
        h = -(-h // stride)
        w = -(-w // stride)
        ch = out_ch
    flat = h * w * ch
    post = list(cfg.fcnet_hiddens) or [64]
    dense = _mlp_init(keys[-1], [flat, *post])
    return {"convs": convs, "dense": dense}, post[-1]


def _lstm_init(rng, in_dim: int, cell: int):
    import jax
    import jax.numpy as jnp
    k1, k2 = jax.random.split(rng)
    scale_x = np.sqrt(1.0 / in_dim)
    scale_h = np.sqrt(1.0 / cell)
    return {
        "wx": jax.random.normal(k1, (in_dim, 4 * cell),
                                jnp.float32) * scale_x,
        "wh": jax.random.normal(k2, (cell, 4 * cell),
                                jnp.float32) * scale_h,
        "b": jnp.zeros((4 * cell,), jnp.float32),
    }


def obs_shape_of(env) -> Tuple[int, ...]:
    """Canonical observation shape for catalog construction: the env's
    declared observation_shape, falling back to (observation_dim,).
    The ONE place this fallback lives — runners and learners must agree
    or they build different networks."""
    shape = tuple(getattr(env, "observation_shape", ()) or ())
    return shape or (int(env.observation_dim),)


def catalog_q_init(rng, obs_shape, num_actions: int, cfg: ModelConfig):
    """Q-network params for the value-based family: torso + Q head only
    (no value torso/head — catalog_q_apply never reads them, and dead
    params would still ride every target copy, adam state, and weight
    broadcast)."""
    import jax
    if cfg.use_lstm:
        raise ValueError("use_lstm is not supported for value-based "
                         "Q networks (R2D2 territory)")
    k_torso, k_pi = jax.random.split(rng)
    torso, feat = _torso_init(k_torso, obs_shape, cfg)
    return {"torso": torso, "pi": _mlp_init(k_pi, [feat, num_actions])}


def catalog_init(rng, obs_shape, num_outputs: int, cfg: ModelConfig):
    """Build the policy/value params pytree for an observation space.

    num_outputs is the pi-head width (action logits for PG-family, Q-values
    for the DQN family — the reference catalog makes the same dual use).
    """
    import jax
    k_torso, k_lstm, k_pi, k_vf, k_vt = jax.random.split(rng, 5)
    torso, feat = _torso_init(k_torso, obs_shape, cfg)
    params = {"torso": torso}
    head_in = feat
    if cfg.use_lstm:
        params["lstm"] = _lstm_init(k_lstm, feat, cfg.lstm_cell_size)
        head_in = cfg.lstm_cell_size
    params["pi"] = _mlp_init(k_pi, [head_in, num_outputs])
    if cfg.vf_share_layers or cfg.use_lstm:
        # Recurrent nets share the torso+cell (reference recurrent_net.py
        # always shares); feed the value head from the same features.
        params["vf"] = _mlp_init(k_vf, [head_in, 1])
    else:
        vt, vfeat = _torso_init(k_vt, obs_shape, cfg)
        params["vf_torso"] = vt
        params["vf"] = _mlp_init(k_vf, [vfeat, 1])
    return params


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------

def _mlp_apply(layers, x, final_act: bool = True):
    import jax.numpy as jnp
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if final_act or i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def _torso_apply(torso, obs, cfg: ModelConfig):
    import jax
    if "layers" in torso:        # MLP
        return _mlp_apply(torso["layers"], obs)
    x = obs
    if x.ndim == 3:              # (B, H, W) -> (B, H, W, 1)
        x = x[..., None]
    filters = cfg.conv_filters or _default_conv_filters(x.shape[1:])
    for conv, (_oc, _k, stride) in zip(torso["convs"], filters):
        x = jax.lax.conv_general_dilated(
            x, conv["w"], window_strides=(stride, stride), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + conv["b"]
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    return _mlp_apply(torso["dense"], x)


def _pi_head(params, feat):
    # 0.01 logit scale: near-uniform initial policy (matches the legacy
    # policy_value nets so learning curves are comparable).
    return _mlp_apply(params["pi"], feat, final_act=False) * 0.01


def _vf_head(params, feat):
    return _mlp_apply(params["vf"], feat, final_act=False)[..., 0]


def _heads(params, feat):
    return _pi_head(params, feat), _vf_head(params, feat)


def _lstm_cell(lstm, x, h, c):
    import jax
    import jax.numpy as jnp
    gates = x @ lstm["wx"] + h @ lstm["wh"] + lstm["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    # Forget-gate bias +1: standard recurrent-net stabilization.
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


def initial_state(batch_size: int, cfg: ModelConfig):
    """Zero (h, c) carry for a recurrent model."""
    import jax.numpy as jnp
    z = jnp.zeros((batch_size, cfg.lstm_cell_size), jnp.float32)
    return (z, z)


def catalog_apply(params, obs, cfg: ModelConfig):
    """Stateless forward [B, ...] -> (logits [B, A], values [B])."""
    assert not cfg.use_lstm, "recurrent model: use catalog_apply_step/seq"
    feat = _torso_apply(params["torso"], obs, cfg)
    pi = _pi_head(params, feat)
    if "vf_torso" in params:
        vfeat = _torso_apply(params["vf_torso"], obs, cfg)
    else:
        vfeat = feat
    return pi, _vf_head(params, vfeat)


def catalog_q_apply(params, obs, cfg: ModelConfig):
    """Q-network forward for the value-based family: the pi head WITHOUT
    the 0.01 near-uniform-policy scale (Q targets grow to episode-return
    magnitude; the policy-gradient init trick would just shrink the last
    layer's effective learning rate). -> Q [B, A]."""
    feat = _torso_apply(params["torso"], obs, cfg)
    return _mlp_apply(params["pi"], feat, final_act=False)


def catalog_apply_step(params, obs, state, cfg: ModelConfig):
    """One recurrent step [B, ...] + (h, c) -> (logits, values, state')."""
    h, state = _recurrent_step(params, obs, state, cfg)
    pi, vf = _heads(params, h)
    return pi, vf, state


def catalog_rq_init(rng, obs_shape, num_actions: int, cfg: ModelConfig):
    """Recurrent Q-network (R2D2 family): torso + LSTM + Q head, no
    value stream, no policy-logit scaling."""
    import jax
    k_torso, k_lstm, k_q = jax.random.split(rng, 3)
    torso, feat = _torso_init(k_torso, obs_shape, cfg)
    return {"torso": torso,
            "lstm": _lstm_init(k_lstm, feat, cfg.lstm_cell_size),
            "pi": _mlp_init(k_q, [cfg.lstm_cell_size, num_actions])}


def _recurrent_step(params, obs, state, cfg: ModelConfig):
    """Shared torso+LSTM step: [B, ...] + (h, c) -> (h', (h', c'))."""
    feat = _torso_apply(params["torso"], obs, cfg)
    h, c = _lstm_cell(params["lstm"], feat, *state)
    return h, (h, c)


def _recurrent_scan(params, obs_seq, done_prev, state_in,
                    cfg: ModelConfig, head_fn):
    """Shared sequence driver: scan the torso+LSTM over [B, T, ...] with
    carry resets where done_prev marks an episode boundary; head_fn maps
    each step's hidden state to the output. The ONE place the boundary
    machinery lives — the policy and Q families must not diverge."""
    import jax
    import jax.numpy as jnp

    obs_tm = jnp.moveaxis(obs_seq, 1, 0)
    done_tm = jnp.moveaxis(done_prev, 1, 0)

    def tick(carry, inp):
        h, c = carry
        obs_t, done_t = inp
        mask = (1.0 - done_t)[:, None]
        h2, carry2 = _recurrent_step(params, obs_t,
                                     (h * mask, c * mask), cfg)
        return carry2, head_fn(params, h2)

    state_out, out_tm = jax.lax.scan(tick, state_in, (obs_tm, done_tm))
    return jax.tree_util.tree_map(
        lambda a: jnp.moveaxis(a, 0, 1), out_tm), state_out


def _q_head(params, h):
    return _mlp_apply(params["pi"], h, final_act=False)


def catalog_rq_apply_step(params, obs, state, cfg: ModelConfig):
    """One recurrent Q step [B, ...] + (h, c) -> (q [B, A], state')."""
    h, state = _recurrent_step(params, obs, state, cfg)
    return _q_head(params, h), state


def catalog_rq_apply_seq(params, obs_seq, done_prev, state_in,
                         cfg: ModelConfig):
    """Recurrent Q over sequences: [B, T, ...] + done_prev [B, T] +
    (h, c) [B, cell] -> (q [B, T, A], state_out); carry resets at
    episode boundaries inside the scan."""
    return _recurrent_scan(params, obs_seq, done_prev, state_in, cfg,
                           _q_head)


def catalog_apply_seq(params, obs_seq, done_prev, state_in,
                      cfg: ModelConfig):
    """Sequence forward for BPTT training.

    obs_seq [B, T, ...], done_prev [B, T] (1.0 where step t-1 ended an
    episode — the carry resets there), state_in (h, c) each [B, cell]
    (the sampler's carry at fragment start). -> (logits [B, T, A],
    values [B, T], state_out).
    """
    (pi, vf), state_out = _recurrent_scan(
        params, obs_seq, done_prev, state_in, cfg, _heads)
    return pi, vf, state_out
