"""ray_tpu.tune: distributed hyperparameter search.

Reference parity: python/ray/tune (Tuner tune/tuner.py:54, TuneController
tune/execution/tune_controller.py:72, Trial tune/experiment/trial.py:247,
ASHA tune/schedulers/async_hyperband.py, PBT tune/schedulers/pbt.py).
Trials run as ray_tpu actors; the controller event-loop drives them with
`wait` and applies scheduler decisions between reports.
"""

from ray_tpu.tune.search import (BasicVariantGenerator, BOHBSearcher,
                                 Categorical, Domain,
                                 Float, GPSearcher, Integer, SearchAlgorithm,
                                 TPESearcher, choice, grid_search,
                                 lograndint, loguniform, qrandint, quniform,
                                 randint, randn, sample_from, uniform)
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.trainable import Trainable, report, get_checkpoint
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import (ResultGrid, Result, TuneConfig, Tuner,
                                run, with_parameters, with_resources)

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Result", "run", "Trainable",
    "Trial", "report", "get_checkpoint", "with_parameters", "with_resources",
    "grid_search", "uniform", "quniform", "loguniform", "choice", "randint",
    "qrandint", "lograndint", "randn", "sample_from",
    "Domain", "Float", "Integer", "Categorical", "BasicVariantGenerator",
    "SearchAlgorithm", "TPESearcher", "GPSearcher", "BOHBSearcher",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "MedianStoppingRule", "PopulationBasedTraining",
]
