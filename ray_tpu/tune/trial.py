"""Trial bookkeeping (reference: python/ray/tune/experiment/trial.py:247)."""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    results: List[dict] = field(default_factory=list)
    error: Optional[str] = None
    iteration: int = 0
    checkpoint: Any = None           # latest in-memory checkpoint blob
    checkpoint_path: Optional[str] = None
    actor: Any = None                # live actor handle while RUNNING
    pending_ref: Any = None          # in-flight next_result ref
    rung: int = 0                    # scheduler bookkeeping (ASHA)

    @property
    def last_result(self) -> Optional[dict]:
        return self.results[-1] if self.results else None

    def metric_history(self, metric: str) -> List[float]:
        return [r[metric] for r in self.results if metric in r]

    def __repr__(self):
        return (f"Trial({self.trial_id}, {self.status}, it={self.iteration}, "
                f"cfg={self.config})")
