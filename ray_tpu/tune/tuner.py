"""Tuner + the trial-driving controller loop.

Reference parity: python/ray/tune/tuner.py:54 (Tuner),
tune/execution/tune_controller.py:72 (event loop over trial actors via the
actor manager). Trials are plain ray_tpu actors; the controller multiplexes
their `next_result` futures with `ray_tpu.wait` and applies scheduler
decisions (CONTINUE/STOP/EXPLOIT) between reports.
"""

from __future__ import annotations

import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.schedulers import (CONTINUE, FIFOScheduler, STOP,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trainable import FunctionRunner, Trainable
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.trial import (ERROR, PENDING, RUNNING, TERMINATED, Trial)


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    # Model-based searcher (e.g. tune.search.TPESearcher): trials are
    # created lazily so each suggestion conditions on completed results.
    search_alg: Optional[Any] = None
    seed: Optional[int] = None

    def __post_init__(self):
        if self.mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")


class _TrialActor:
    """Hosts one trainable (class or function) inside an actor."""

    def __init__(self, trainable_blob: bytes, config: dict,
                 checkpoint: Any = None, start_iteration: int = 0):
        import cloudpickle
        trainable = cloudpickle.loads(trainable_blob)
        self._is_class = isinstance(trainable, type) and issubclass(
            trainable, Trainable)
        # Restart paths (PBT exploit) resume the iteration counter so stop
        # criteria and perturbation schedules don't rewind.
        self._iteration = start_iteration
        if self._is_class:
            self._inst = trainable(config)
            if checkpoint is not None:
                self._inst.load_checkpoint(checkpoint)
        else:
            self._runner = FunctionRunner(trainable, config, checkpoint)

    def next_result(self):
        """-> (kind, payload, checkpoint) with kind in
        result|done|error|pending."""
        if self._is_class:
            try:
                metrics = self._inst.step()
                self._iteration += 1
                self._inst.training_iteration = self._iteration
                metrics.setdefault("training_iteration", self._iteration)
                return ("result", metrics, None)
            except Exception:
                import traceback
                return ("error", traceback.format_exc(), None)
        kind, payload, ckpt = self._runner.next_result(timeout=3600.0)
        if kind == "result":
            self._iteration += 1
            payload.setdefault("training_iteration", self._iteration)
        return (kind, payload, ckpt)

    def save(self):
        if self._is_class:
            return self._inst.save_checkpoint()
        return self._runner.save()

    def reset(self, new_config: dict, checkpoint: Any) -> bool:
        if self._is_class and self._inst.reset_config(new_config):
            self._inst.config = dict(new_config)
            if checkpoint is not None:
                self._inst.load_checkpoint(checkpoint)
            return True
        return False

    def stop(self):
        if self._is_class:
            self._inst.cleanup()
        return True


@dataclass
class Result:
    metrics: Optional[dict]
    config: dict
    error: Optional[str] = None
    checkpoint: Any = None
    metrics_history: List[dict] = field(default_factory=list)


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        t = self._trials[i]
        return Result(metrics=t.last_result, config=t.config, error=t.error,
                      checkpoint=t.checkpoint, metrics_history=t.results)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        sign = 1.0 if mode == "max" else -1.0
        best_t, best_s, best_r = None, None, None
        for t in self._trials:
            for r in t.results:
                if metric not in r:
                    continue
                s = sign * r[metric]
                if best_s is None or s > best_s:
                    best_t, best_s, best_r = t, s, r
        if best_t is None:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        # Return the best-scoring report itself, not the trial's last one —
        # a trial that peaked then collapsed must not surface its collapsed
        # metrics as "best".
        return Result(metrics=best_r, config=best_t.config,
                      error=best_t.error, checkpoint=best_t.checkpoint,
                      metrics_history=best_t.results)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for t in self._trials:
            row = dict(t.last_result or {})
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Union[Callable, type], *,
                 param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._resources = getattr(trainable, "_tune_resources",
                                  {"num_cpus": 1})
        self._restored_trials: Optional[List[Trial]] = None
        self._restored_dir: Optional[str] = None
        self._trainable_blob: Optional[bytes] = None
        self._last_state_save = 0.0

    # -- experiment persistence (reference: Tuner.restore /
    #    tune/execution/experiment_state.py) ---------------------------

    def _experiment_dir(self) -> Optional[str]:
        # A restored experiment keeps persisting to the directory it was
        # restored FROM (the tree may have been moved between machines).
        if self._restored_dir is not None:
            return self._restored_dir
        rc = self._run_config
        if not rc.storage_path:
            return None
        return os.path.join(rc.storage_path, rc.name or "tune_experiment")

    def _save_experiment_state(self, trials: List[Trial],
                               min_interval: float = 1.0):
        exp_dir = self._experiment_dir()
        if exp_dir is None:
            return
        now = time.time()
        if now - self._last_state_save < min_interval:
            return  # rate limit: terminate bursts / per-result hooks
        self._last_state_save = now
        import cloudpickle
        try:
            os.makedirs(exp_dir, exist_ok=True)
            snapshot = []
            for t in trials:
                snapshot.append({
                    "config": t.config, "trial_id": t.trial_id,
                    "status": t.status, "results": t.results,
                    "error": t.error, "iteration": t.iteration,
                    "checkpoint": t.checkpoint, "rung": t.rung,
                })
            if self._trainable_blob is None:
                self._trainable_blob = cloudpickle.dumps(self._trainable)
            state = {"trials": snapshot,
                     "param_space": self._param_space,
                     "tune_config": self._tune_config,
                     "run_config": self._run_config,
                     "trainable": self._trainable_blob}
            tmp = os.path.join(exp_dir, ".experiment_state.tmp")
            with open(tmp, "wb") as f:
                cloudpickle.dump(state, f)
            os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))
        except Exception:  # noqa: BLE001
            # Persistence must never kill the live experiment (disk full,
            # flaky mount): the run continues, resume just gets older state.
            import logging
            logging.getLogger(__name__).exception(
                "experiment state save failed (continuing)")

    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, type, None] = None
                ) -> "Tuner":
        """Resume an interrupted experiment from its storage directory.

        Finished trials keep their results; trials that were RUNNING or
        PENDING restart from their latest checkpoint + iteration
        (reference: python/ray/tune/tuner.py Tuner.restore)."""
        import cloudpickle
        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = cloudpickle.load(f)
        tuner = cls(trainable if trainable is not None
                    else cloudpickle.loads(state["trainable"]),
                    param_space=state["param_space"],
                    tune_config=state["tune_config"],
                    run_config=state["run_config"])
        trials = []
        for s in state["trials"]:
            t = Trial(config=s["config"], trial_id=s["trial_id"])
            t.results = s["results"]
            t.error = s["error"]
            t.iteration = s["iteration"]
            t.checkpoint = s["checkpoint"]
            t.rung = s.get("rung", 0)
            # Interrupted trials resume; finished ones stay finished.
            t.status = (s["status"] if s["status"] in (TERMINATED, ERROR)
                        else PENDING)
            trials.append(t)
        tuner._restored_trials = trials
        tuner._restored_dir = path
        return tuner

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(os.path.join(path, "experiment_state.pkl"))

    def fit(self) -> ResultGrid:
        import cloudpickle
        tc = self._tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        if tc.metric:
            scheduler.set_metric(tc.metric, tc.mode)
        elif not isinstance(scheduler, FIFOScheduler):
            raise ValueError("schedulers other than FIFO require a metric")
        searcher = tc.search_alg if self._restored_trials is None else None
        if searcher is not None:
            if not tc.metric:
                raise ValueError("search_alg requires TuneConfig.metric")
            searcher.set_space(self._param_space)
            searcher.set_metric(tc.metric, tc.mode)
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif searcher is not None:
            trials = []  # created lazily from searcher suggestions
        else:
            variants = BasicVariantGenerator(
                self._param_space, tc.num_samples, tc.seed).variants()
            trials = [Trial(config=cfg) for cfg in variants]
        blob = cloudpickle.dumps(self._trainable)
        stop = self._run_config.stop or {}

        try:
            cpus = ray_tpu.cluster_resources().get("CPU", 2)
        except Exception:
            cpus = 2
        trial_cpus = float(self._resources.get("num_cpus", 1)) or 1
        max_conc = tc.max_concurrent_trials or max(1, int(cpus // trial_cpus))
        actor_cls = ray_tpu.remote(**self._resources)(_TrialActor)

        def start(t: Trial, checkpoint=None, config=None,
                  start_iteration: int = 0):
            t.actor = actor_cls.remote(blob, config or t.config, checkpoint,
                                       start_iteration)
            t.status = RUNNING
            t.pending_ref = t.actor.next_result.remote()

        def terminate(t: Trial, status: str):
            t.status = status
            # Terminal statuses only: PBT's exploit path calls
            # terminate(t, RUNNING) to restart an actor mid-trial, which
            # must not feed a bogus completion into the searcher.
            if searcher is not None and status in (TERMINATED, ERROR):
                try:
                    searcher.on_trial_complete(t.trial_id, t.last_result)
                except Exception:
                    pass
            if t.actor is not None:
                try:
                    # Run the Trainable.cleanup() hook before killing the
                    # process (kill alone would leak user resources).
                    ray_tpu.get(t.actor.stop.remote(), timeout=5)
                except Exception:
                    pass
                try:
                    ray_tpu.kill(t.actor)
                except Exception:
                    pass
                t.actor = None
            t.pending_ref = None
            self._save_experiment_state(trials)

        def should_stop(t: Trial, metrics: dict) -> bool:
            for k, v in stop.items():
                if k == "training_iteration":
                    if metrics.get(k, t.iteration) >= v:
                        return True
                elif k in metrics:
                    cmp = metrics[k]
                    if (tc.mode == "max" and cmp >= v) or \
                       (tc.mode == "min" and cmp <= v):
                        return True
            return False

        pace = getattr(scheduler, "pace_interval", None)

        def live_min_iteration():
            live = [t for t in trials if t.status in (PENDING, RUNNING)]
            return min((t.iteration for t in live), default=0)

        def resume_if_caught_up():
            """Paced trials (pending_ref=None) resume once peers catch up."""
            if pace is None:
                return
            floor = live_min_iteration()
            for t in trials:
                if (t.status == RUNNING and t.pending_ref is None
                        and t.actor is not None
                        and t.iteration - floor < pace):
                    t.pending_ref = t.actor.next_result.remote()

        def submit_next(t: Trial):
            if pace is not None and t.iteration - live_min_iteration() >= pace:
                t.pending_ref = None  # paced: resumed by resume_if_caught_up
            else:
                t.pending_ref = t.actor.next_result.remote()

        searcher_done = searcher is None

        def spawn_from_searcher(running, pending):
            """Lazily create trials so each suggestion sees prior results."""
            nonlocal searcher_done
            import uuid as _uuid
            while (not searcher_done and len(trials) < tc.num_samples
                   and len(running) + len(pending) < max_conc):
                tid = _uuid.uuid4().hex[:8]
                cfg = searcher.suggest(tid)
                if cfg is None:
                    searcher_done = True
                    return
                nt = Trial(config=cfg, trial_id=tid)
                trials.append(nt)
                pending.append(nt)
            if len(trials) >= tc.num_samples:
                searcher_done = True

        while True:
            running = [t for t in trials if t.status == RUNNING]
            pending = [t for t in trials if t.status == PENDING]
            if searcher is not None:
                spawn_from_searcher(running, pending)
            if not running and not pending:
                break
            while pending and len(running) < max_conc:
                t = pending.pop(0)
                # Restored trials resume from their checkpoint/iteration.
                start(t, checkpoint=t.checkpoint,
                      start_iteration=t.iteration)
                running.append(t)
            resume_if_caught_up()
            ref_to_trial = {t.pending_ref: t for t in running
                            if t.pending_ref is not None}
            if not ref_to_trial:
                paced = [t for t in running if t.pending_ref is None
                         and t.actor is not None]
                if paced and not pending:
                    time.sleep(0.05)
                    continue
                if paced:
                    # All in-flight slots are paced trials but pending trials
                    # can't start (resources held): abandon pacing rather
                    # than deadlock.
                    for t in paced:
                        t.pending_ref = t.actor.next_result.remote()
                    continue
                time.sleep(0.05)
                continue
            done, _ = ray_tpu.wait(list(ref_to_trial.keys()),
                                   num_returns=1, timeout=5.0)
            for ref in done:
                t = ref_to_trial[ref]
                try:
                    kind, payload, ckpt = ray_tpu.get(ref)
                except Exception as e:
                    t.error = str(e)
                    terminate(t, ERROR)
                    continue
                if kind == "done":
                    terminate(t, TERMINATED)
                elif kind == "error":
                    t.error = payload
                    terminate(t, ERROR)
                elif kind == "pending":
                    submit_next(t)
                else:  # result
                    t.iteration = payload.get("training_iteration",
                                              t.iteration + 1)
                    t.results.append(payload)
                    if ckpt is not None:
                        t.checkpoint = ckpt
                    if should_stop(t, payload):
                        terminate(t, TERMINATED)
                        continue
                    decision = scheduler.on_trial_result(t, payload, trials)
                    if decision == STOP:
                        terminate(t, TERMINATED)
                    elif decision == "EXPLOIT":
                        self._exploit(t, scheduler, start, terminate)
                    else:
                        submit_next(t)
                    if t.results and len(t.results) % 10 == 0:
                        self._save_experiment_state(trials)
        self._save_experiment_state(trials, min_interval=0.0)
        return ResultGrid(trials, tc.metric, tc.mode)

    def _exploit(self, t: Trial, scheduler, start, terminate):
        """PBT: clone a top trial's checkpoint + perturbed config."""
        target: Trial = getattr(t, "_exploit_target", None)
        if target is None or target.actor is None:
            t.pending_ref = t.actor.next_result.remote()
            return
        assert isinstance(scheduler, PopulationBasedTraining)
        try:
            ckpt = ray_tpu.get(target.actor.save.remote(), timeout=30)
        except Exception:
            t.pending_ref = t.actor.next_result.remote()
            return
        new_config = scheduler.explore(target.config)
        # Try in-place reset first; else restart the actor.
        reset_ok = False
        try:
            reset_ok = ray_tpu.get(
                t.actor.reset.remote(new_config, ckpt), timeout=30)
        except Exception:
            pass
        t.config = new_config
        t.checkpoint = ckpt
        if reset_ok:
            t.pending_ref = t.actor.next_result.remote()
        else:
            terminate(t, RUNNING)  # kill actor, keep status RUNNING
            start(t, checkpoint=ckpt, config=new_config,
                  start_iteration=t.iteration)


def with_parameters(trainable, **params):
    """Bind large constant objects to a trainable (reference:
    tune.with_parameters)."""
    if isinstance(trainable, type):
        class _Bound(trainable):  # type: ignore[misc]
            def setup(self, config):
                super().setup({**config, **params})
        _Bound.__name__ = trainable.__name__
        return _Bound

    def fn(config):
        return trainable(config, **params)
    fn._tune_resources = getattr(trainable, "_tune_resources",
                                 {"num_cpus": 1})
    return fn


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resource requests ({"num_cpus": 2, "num_tpus": 1})."""
    trainable._tune_resources = resources
    return trainable


def run(trainable, *, config: Optional[dict] = None, stop=None,
        metric=None, mode="max", num_samples: int = 1, scheduler=None,
        **_ignored) -> ResultGrid:
    """Legacy tune.run() façade over Tuner."""
    return Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
        run_config=RunConfig(stop=stop),
    ).fit()
