"""Search spaces + variant generation.

Reference parity: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator, search_algorithm.py:10 ABC).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lo: float, hi: float, log: bool = False,
                 q: Optional[float] = None):
        self.lo, self.hi, self.log, self.q = lo, hi, log, q

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lo), np.log(self.hi))))
        else:
            v = rng.uniform(self.lo, self.hi)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lo: int, hi: int, log: bool = False,
                 q: Optional[int] = None):
        self.lo, self.hi, self.log, self.q = lo, hi, log, q

    def sample(self, rng):
        if self.log:
            v = int(np.exp(rng.uniform(np.log(self.lo),
                                       np.log(max(self.hi - 1, self.lo + 1)))))
        else:
            v = rng.randint(self.lo, self.hi - 1)
        if self.q:
            v = int(round(v / self.q) * self.q)
        return max(self.lo, min(v, self.hi - 1))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None) if self.fn.__code__.co_argcount else self.fn()


def uniform(lo, hi) -> Float:
    return Float(lo, hi)


def quniform(lo, hi, q) -> Float:
    return Float(lo, hi, q=q)


def loguniform(lo, hi) -> Float:
    return Float(lo, hi, log=True)


def randint(lo, hi) -> Integer:
    return Integer(lo, hi)


def qrandint(lo, hi, q) -> Integer:
    return Integer(lo, hi, q=q)


def lograndint(lo, hi) -> Integer:
    return Integer(lo, hi, log=True)


def randn(mean=0.0, sd=1.0) -> Normal:
    return Normal(mean, sd)


def choice(categories) -> Categorical:
    return Categorical(categories)


def sample_from(fn) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _split_grid(space: dict, prefix=()):
    """Yield (path, values) for every grid_search leaf."""
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            yield prefix + (k,), v["grid_search"]
        elif isinstance(v, dict):
            yield from _split_grid(v, prefix + (k,))


def _set_path(cfg: dict, path, value):
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _resolve(space, rng, out):
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            continue  # filled by grid expansion
        elif isinstance(v, dict):
            out[k] = {}
            _resolve(v, rng, out[k])
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        else:
            out[k] = v
    return out


class SearchAlgorithm:
    """ABC (reference: search/search_algorithm.py:10 + searcher.py Searcher).

    Incremental protocol: the controller calls ``suggest(trial_id)`` for
    each new trial slot and feeds results back via ``on_trial_complete``;
    model-based searchers condition later suggestions on earlier results.
    """

    def set_space(self, space: dict):
        self._space = space

    def set_metric(self, metric: str, mode: str):
        self._metric = metric
        self._mode = mode

    def suggest(self, trial_id: str) -> Optional[dict]:
        """Next config to evaluate, or None when exhausted."""
        raise NotImplementedError

    def next_configs(self, n: int) -> List[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        pass


def _flatten_domains(space, prefix=()):
    """Yield (path, Domain-or-constant) for every non-grid leaf."""
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            raise ValueError("grid_search is not supported by model-based "
                             "searchers; use BasicVariantGenerator")
        if isinstance(v, dict):
            yield from _flatten_domains(v, prefix + (k,))
        else:
            yield prefix + (k,), v


class TPESearcher(SearchAlgorithm):
    """Native Tree-structured Parzen Estimator (Bergstra et al., NeurIPS'11).

    Reference capability: python/ray/tune/search/optuna/optuna_search.py and
    hyperopt/hyperopt_search.py wrap external TPE implementations; here the
    estimator is built in (no dependency):

    - observations are split at the gamma-quantile into good (l) and bad (g)
    - numeric dims: Parzen window (gaussian KDE, Scott bandwidth with a
      floor) per side, in log space for log domains; n_candidates are drawn
      from l and the one maximizing l(x)/g(x) wins (expected-improvement
      maximizer for the TPE objective)
    - categorical dims: smoothed category frequencies on each side, same
      ratio criterion
    - first n_initial suggestions are random (seeded) to prime the model
    """

    def __init__(self, space: Optional[dict] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 n_initial: int = 10, n_candidates: int = 24,
                 gamma: float = 0.25, seed: Optional[int] = None):
        if space is not None:
            self.set_space(space)
        self._metric = metric
        self._mode = mode
        self._n_initial = n_initial
        self._n_candidates = n_candidates
        self._gamma = gamma
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(seed)
        # trial_id -> (flat config dict, score or None)
        self._live: Dict[str, dict] = {}
        self._obs: List[Tuple[dict, float]] = []
        self._n_suggested = 0

    # -- protocol ------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        domains = dict(_flatten_domains(self._space))
        self._n_suggested += 1
        # Every 4th post-warmup suggestion samples the prior: the factorized
        # estimator can lock onto a local basin (observed on both numeric
        # and categorical dims); guaranteed exploration lets the model jump
        # to a better basin the moment one random trial lands in it.
        obs = self._observations()
        explore = (self._warmed_up(obs)
                   and self._n_suggested % 4 == 0)
        if not self._warmed_up(obs) or explore:
            flat = {p: (d.sample(self._rng) if isinstance(d, Domain) else d)
                    for p, d in domains.items()}
        else:
            split = self._split()  # dimension-independent: compute once
            flat = {p: self._suggest_dim(p, d, split)
                    for p, d in domains.items()}
        self._live[trial_id] = flat
        cfg: dict = {}
        for path, v in flat.items():
            _set_path(cfg, path, v)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        flat = self._live.pop(trial_id, None)
        if flat is None or not result or self._metric not in result:
            return
        sign = 1.0 if self._mode == "max" else -1.0
        self._obs.append((flat, sign * float(result[self._metric])))

    def _observations(self):
        """Observation list the estimator conditions on (BOHB overrides
        this with a per-budget selection)."""
        return self._obs

    def _warmed_up(self, obs) -> bool:
        """Random warmup is over: the estimator may model."""
        return len(obs) >= self._n_initial

    # -- estimator -----------------------------------------------------

    def _split(self):
        """(good, bad) observation lists, each entry (flat, score, age_w).

        age_w implements hyperopt-style linear forgetting: the latest 25
        observations weigh 1.0, older ones ramp down linearly. Early trials
        mis-blame dimensions (a good category tried with a bad numeric
        lands in the bad set and is never retried — observed lock-in);
        decaying stale evidence lets the marginal recover.
        """
        obs_src = self._observations()
        n = len(obs_src)
        ramp = 25

        def age_w(idx):
            if n <= ramp or idx >= n - ramp:
                return 1.0
            return max(1.0 / ramp, (idx + 1) / (n - ramp))

        obs = sorted(
            ((flat, score, age_w(i))
             for i, (flat, score) in enumerate(obs_src)),
            key=lambda o: -o[1])
        # Hyperopt's split size: ceil(gamma * sqrt(n)) capped at 25 — a
        # small elite set means one newly-found better basin immediately
        # dominates the good-side density (a linear-in-n good set keeps the
        # incumbent cluster in charge and relocks).
        n_good = max(1, min(25, int(np.ceil(self._gamma * np.sqrt(n)))))
        return obs[:n_good], obs[n_good:]

    def _suggest_dim(self, path, dom, split):
        if not isinstance(dom, Domain):
            return dom
        good, bad = split
        gx = [(o[0][path], o[2]) for o in good if path in o[0]]
        bx = [(o[0][path], o[2]) for o in bad if path in o[0]]
        if isinstance(dom, Categorical):
            return self._suggest_categorical(dom, gx, bx)
        if isinstance(dom, (Float, Integer, Normal)):
            return self._suggest_numeric(dom, [v for v, _ in gx],
                                         [v for v, _ in bx])
        return dom.sample(self._rng)

    def _suggest_categorical(self, dom: Categorical, gx, bx):
        cats = dom.categories
        # Laplace-smoothed, age-weighted frequencies on each side.
        def freqs(xs):
            counts = np.array([1.0 + sum(w for x, w in xs if x == c)
                               for c in cats])
            return counts / counts.sum()
        lf, gf = freqs(gx), freqs(bx)
        # Every category competes on the l/g ratio (the domain is small, so
        # no need to subsample candidates — and it removes draw-order luck).
        best = max(range(len(cats)), key=lambda i: lf[i] / gf[i])
        return cats[int(best)]

    def _numeric_transform(self, dom, x):
        x = np.asarray(x, dtype=np.float64)
        return np.log(x) if getattr(dom, "log", False) else x

    def _numeric_untransform(self, dom, x):
        v = float(np.exp(x)) if getattr(dom, "log", False) else float(x)
        if isinstance(dom, Integer):
            v = int(round(v))
            if dom.q:
                v = int(round(v / dom.q) * dom.q)
            return max(dom.lo, min(v, dom.hi - 1))
        if isinstance(dom, Float):
            if dom.q:
                v = round(v / dom.q) * dom.q
            return min(max(v, dom.lo), dom.hi)
        return v

    def _bounds(self, dom):
        if isinstance(dom, (Float, Integer)):
            lo, hi = float(dom.lo), float(dom.hi)
            if getattr(dom, "log", False):
                return np.log(lo), np.log(hi)
            return lo, hi
        return -np.inf, np.inf

    def _kde(self, dom, xs):
        """Per-component (means, bandwidths) of the Parzen mixture.

        Hyperopt-style: each observation gets a bandwidth equal to its
        larger neighbor gap (clipped to [span/50, span]), and a wide prior
        component at the domain center joins the mixture — without it the
        estimator collapses onto the incumbent cluster and crawls
        (measured: ~0.01/step drift on a 1D quadratic)."""
        lo, hi = self._bounds(dom)
        if np.isfinite(hi - lo):
            span = hi - lo
            prior_mu = (hi + lo) / 2
        else:
            span = (np.std(xs) * 6 + 1.0) if len(xs) else 1.0
            prior_mu = float(np.mean(xs)) if len(xs) else 0.0
        if len(xs) == 0:
            return (np.array([prior_mu]), np.array([max(span, 1e-12)]),
                    np.array([1.0]))
        xs = np.sort(np.asarray(xs, dtype=np.float64))
        gaps_left = np.diff(xs, prepend=xs[0] - span)
        gaps_right = np.diff(xs, append=xs[-1] + span)
        bws = np.clip(np.maximum(gaps_left, gaps_right),
                      span / 50.0, span)
        means = np.append(xs, prior_mu)
        bws = np.append(bws, span)
        # The prior keeps ~25% of the mixture mass: pure observation
        # mixtures collapse onto the incumbent cluster and crawl toward
        # distant optima one bandwidth per round.
        weights = np.append(np.ones(len(xs)), max(1.0, 0.33 * len(xs)))
        return means, bws, weights / weights.sum()

    @staticmethod
    def _log_pdf(x, means, bws, weights):
        z = (x[:, None] - means[None, :]) / bws[None, :]
        comp = (-0.5 * z * z - np.log(bws[None, :] * np.sqrt(2 * np.pi))
                + np.log(weights[None, :]))
        m = comp.max(axis=1)
        return m + np.log(np.sum(np.exp(comp - m[:, None]), axis=1))

    def _suggest_numeric(self, dom, gx, bx):
        gt = self._numeric_transform(dom, gx) if len(gx) else np.array([])
        bt = self._numeric_transform(dom, bx) if len(bx) else np.array([])
        l_means, l_bws, l_w = self._kde(dom, gt)
        g_means, g_bws, g_w = self._kde(dom, bt)
        lo, hi = self._bounds(dom)
        # Sample candidates from l (components by weight).
        picks = self._np_rng.choice(len(l_means), size=self._n_candidates,
                                    p=l_w)
        cands = (l_means[picks]
                 + self._np_rng.randn(self._n_candidates) * l_bws[picks])
        if np.isfinite(lo):
            # Reflect out-of-range candidates back inside instead of
            # clipping: clipping piles a point-mass on the boundary that
            # self-reinforces (observed: lr stuck at the domain edge).
            span = hi - lo
            cands = np.abs(cands - lo) % (2 * span)
            cands = lo + np.where(cands > span, 2 * span - cands, cands)
        score = (self._log_pdf(cands, l_means, l_bws, l_w)
                 - self._log_pdf(cands, g_means, g_bws, g_w))
        return self._numeric_untransform(dom, cands[int(np.argmax(score))])


class BOHBSearcher(TPESearcher):
    """BOHB's model side (Falkner et al., ICML'18): TPE conditioned on the
    LARGEST budget that has enough observations.

    Reference capability: python/ray/tune/search/bohb/bohb_search.py wraps
    the external hpbandster package; here it reuses the native TPE
    estimator. Pair with AsyncHyperBandScheduler (the ASHA rungs supply
    the budgets): results report their budget via `budget_key`
    (default "training_iteration"), and suggestions are conditioned on
    the highest budget whose observation count reaches `min_points`,
    pooling everything when no budget qualifies yet.
    """

    def __init__(self, *args, budget_key: str = "training_iteration",
                 min_points: Optional[int] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self._budget_key = budget_key
        self._min_points = min_points
        self._budget_obs: Dict[float, list] = {}

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        flat = self._live.pop(trial_id, None)
        if flat is None or not result or self._metric not in result:
            return
        sign = 1.0 if self._mode == "max" else -1.0
        budget = float(result.get(self._budget_key, 1.0))
        entry = (flat, sign * float(result[self._metric]))
        self._budget_obs.setdefault(budget, []).append(entry)
        self._obs.append(entry)  # pooled fallback

    def _observations(self):
        dims = sum(1 for _p, d in _flatten_domains(self._space)
                   if isinstance(d, Domain))
        need = self._min_points or max(dims + 1, self._n_initial)
        for budget in sorted(self._budget_obs, reverse=True):
            if len(self._budget_obs[budget]) >= need:
                return self._budget_obs[budget]
        return self._obs

    def _warmed_up(self, obs) -> bool:
        # BOHB warms up on the POOLED count: once enough total trials
        # exist the model runs, even when the selected (highest adequate)
        # budget's own list is smaller than n_initial — min_points
        # declared that list big enough to condition on.
        return len(self._obs) >= self._n_initial


class GPSearcher(SearchAlgorithm):
    """Native Gaussian-process Bayesian optimization with Expected
    Improvement.

    Reference capability: python/ray/tune/search/bayesopt/bayesopt_search.py
    wraps the external `bayes_opt` package (GP + acquisition); here the GP
    is built in (numpy Cholesky posterior):

    - numeric dims normalized to [0,1] (log-space for log domains);
      Categorical dims are one-hot relaxed (argmax on suggestion)
    - Matérn-5/2 kernel with a fitted-by-grid lengthscale and noise floor
    - acquisition: EI maximized over a quasi-random candidate sweep plus
      jittered copies of the incumbent (local refinement)
    - first n_initial suggestions random (seeded) to prime the GP
    """

    def __init__(self, space: Optional[dict] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 n_initial: int = 8, n_candidates: int = 512,
                 xi: float = 0.01, seed: Optional[int] = None):
        if space is not None:
            self.set_space(space)
        self._metric = metric
        self._mode = mode
        self._n_initial = n_initial
        self._n_candidates = n_candidates
        self._xi = xi
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(seed)
        self._live: Dict[str, dict] = {}
        self._obs: List[tuple] = []   # (unit-cube vector, flat cfg, score)

    # -- dimension encoding --------------------------------------------

    def _dims(self):
        out = []
        for path, dom in _flatten_domains(self._space):
            if isinstance(dom, (Float, Integer)):
                out.append((path, dom, 1))
            elif isinstance(dom, Categorical):
                out.append((path, dom, len(dom.categories)))
            elif isinstance(dom, Domain):
                raise ValueError(f"GPSearcher cannot model {type(dom).__name__}"
                                 f" at {path}; use TPESearcher")
            else:
                out.append((path, dom, 0))  # constant
        return out

    def _to_unit(self, dom, v):
        lo, hi = float(dom.lo), float(dom.hi)
        if getattr(dom, "log", False):
            return (np.log(v) - np.log(lo)) / (np.log(hi) - np.log(lo))
        return (float(v) - lo) / (hi - lo)

    def _from_unit(self, dom, u):
        u = min(max(float(u), 0.0), 1.0)
        lo, hi = float(dom.lo), float(dom.hi)
        if getattr(dom, "log", False):
            v = float(np.exp(np.log(lo) + u * (np.log(hi) - np.log(lo))))
        else:
            v = lo + u * (hi - lo)
        if isinstance(dom, Integer):
            v = int(round(v))
            if dom.q:
                v = int(round(v / dom.q) * dom.q)
            return max(dom.lo, min(v, dom.hi - 1))
        if dom.q:
            v = round(v / dom.q) * dom.q
        return min(max(v, dom.lo), dom.hi)

    def _vec_of(self, flat):
        parts = []
        for path, dom, width in self._dims():
            if width == 0:
                continue
            v = flat[path]
            if isinstance(dom, Categorical):
                one = np.zeros(width)
                one[dom.categories.index(v)] = 1.0
                parts.append(one)
            else:
                parts.append(np.array([self._to_unit(dom, v)]))
        return np.concatenate(parts) if parts else np.zeros(1)

    def _flat_of(self, vec):
        flat, off = {}, 0
        for path, dom, width in self._dims():
            if width == 0:
                flat[path] = dom
                continue
            if isinstance(dom, Categorical):
                flat[path] = dom.categories[int(np.argmax(
                    vec[off:off + width]))]
            else:
                flat[path] = self._from_unit(dom, vec[off])
            off += width
        return flat

    # -- protocol ------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[dict]:
        dims = self._dims()
        if len(self._obs) < self._n_initial:
            flat = {p: (d.sample(self._rng) if isinstance(d, Domain) else d)
                    for p, d, _ in dims}
        else:
            flat = self._flat_of(self._acquire())
        self._live[trial_id] = flat
        cfg: dict = {}
        for path, v in flat.items():
            _set_path(cfg, path, v)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        flat = self._live.pop(trial_id, None)
        if flat is None or not result or self._metric not in result:
            return
        sign = 1.0 if self._mode == "max" else -1.0
        self._obs.append((self._vec_of(flat), flat,
                          sign * float(result[self._metric])))

    # -- GP ------------------------------------------------------------

    @staticmethod
    def _matern52(X1, X2, ls):
        d = np.sqrt(np.maximum(
            ((X1[:, None, :] - X2[None, :, :]) ** 2).sum(-1), 1e-18)) / ls
        return (1 + np.sqrt(5) * d + 5 * d * d / 3) * np.exp(-np.sqrt(5) * d)

    def _posterior(self, Xc):
        X = np.stack([v for v, _f, _s in self._obs])
        y = np.array([s for _v, _f, s in self._obs], dtype=np.float64)
        mu0, sd = y.mean(), max(y.std(), 1e-9)
        yn = (y - mu0) / sd
        noise = 1e-6
        best_ls, best_ll = 0.5, -np.inf
        for ls in (0.1, 0.2, 0.5, 1.0, 2.0):   # marginal-likelihood grid
            K = self._matern52(X, X, ls) + noise * np.eye(len(X))
            try:
                L = np.linalg.cholesky(K)
            except np.linalg.LinAlgError:
                continue
            a = np.linalg.solve(L.T, np.linalg.solve(L, yn))
            ll = (-0.5 * yn @ a - np.log(np.diag(L)).sum())
            if ll > best_ll:
                best_ls, best_ll = ls, ll
        K = self._matern52(X, X, best_ls) + noise * np.eye(len(X))
        L = np.linalg.cholesky(K + 1e-12 * np.eye(len(X)))
        a = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        Ks = self._matern52(Xc, X, best_ls)
        mu = Ks @ a
        v = np.linalg.solve(L, Ks.T)
        var = np.maximum(1.0 - (v * v).sum(axis=0), 1e-12)
        return mu * sd + mu0, np.sqrt(var) * sd, y.max()

    def _acquire(self):
        dim = sum(w for _p, _d, w in self._dims() if w)
        cands = self._np_rng.rand(self._n_candidates, dim)
        # local refinement: jittered copies of the best few observations
        top = sorted(self._obs, key=lambda o: -o[2])[:4]
        local = np.concatenate([
            np.clip(v[None, :] + 0.05 * self._np_rng.randn(16, dim), 0, 1)
            for v, _f, _s in top]) if top else np.zeros((0, dim))
        Xc = np.vstack([cands, local])
        mu, sigma, best = self._posterior(Xc)
        imp = mu - best - self._xi
        z = imp / sigma
        # EI = imp * Phi(z) + sigma * phi(z)
        phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1 + _erf_vec(z / np.sqrt(2)))
        ei = imp * Phi + sigma * phi
        return Xc[int(np.argmax(ei))]


def _erf_vec(x):
    from math import erf
    return np.vectorize(erf)(x)


class BasicVariantGenerator(SearchAlgorithm):
    """Grid expansion × random sampling (reference: basic_variant.py)."""

    def __init__(self, space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = space
        self._num_samples = num_samples
        self._rng = random.Random(seed)

    def variants(self) -> List[dict]:
        grids = list(_split_grid(self._space))
        out = []
        for _ in range(self._num_samples):
            if grids:
                paths, values = zip(*grids)
                for combo in itertools.product(*values):
                    cfg = _resolve(self._space, self._rng, {})
                    for path, val in zip(paths, combo):
                        _set_path(cfg, path, val)
                    out.append(cfg)
            else:
                out.append(_resolve(self._space, self._rng, {}))
        return out
