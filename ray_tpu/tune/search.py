"""Search spaces + variant generation.

Reference parity: python/ray/tune/search/ (sample.py domains,
basic_variant.py BasicVariantGenerator, search_algorithm.py:10 ABC).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lo: float, hi: float, log: bool = False,
                 q: Optional[float] = None):
        self.lo, self.hi, self.log, self.q = lo, hi, log, q

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lo), np.log(self.hi))))
        else:
            v = rng.uniform(self.lo, self.hi)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lo: int, hi: int, log: bool = False,
                 q: Optional[int] = None):
        self.lo, self.hi, self.log, self.q = lo, hi, log, q

    def sample(self, rng):
        if self.log:
            v = int(np.exp(rng.uniform(np.log(self.lo),
                                       np.log(max(self.hi - 1, self.lo + 1)))))
        else:
            v = rng.randint(self.lo, self.hi - 1)
        if self.q:
            v = int(round(v / self.q) * self.q)
        return max(self.lo, min(v, self.hi - 1))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None) if self.fn.__code__.co_argcount else self.fn()


def uniform(lo, hi) -> Float:
    return Float(lo, hi)


def quniform(lo, hi, q) -> Float:
    return Float(lo, hi, q=q)


def loguniform(lo, hi) -> Float:
    return Float(lo, hi, log=True)


def randint(lo, hi) -> Integer:
    return Integer(lo, hi)


def qrandint(lo, hi, q) -> Integer:
    return Integer(lo, hi, q=q)


def lograndint(lo, hi) -> Integer:
    return Integer(lo, hi, log=True)


def randn(mean=0.0, sd=1.0) -> Normal:
    return Normal(mean, sd)


def choice(categories) -> Categorical:
    return Categorical(categories)


def sample_from(fn) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _split_grid(space: dict, prefix=()):
    """Yield (path, values) for every grid_search leaf."""
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            yield prefix + (k,), v["grid_search"]
        elif isinstance(v, dict):
            yield from _split_grid(v, prefix + (k,))


def _set_path(cfg: dict, path, value):
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _resolve(space, rng, out):
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            continue  # filled by grid expansion
        elif isinstance(v, dict):
            out[k] = {}
            _resolve(v, rng, out[k])
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        else:
            out[k] = v
    return out


class SearchAlgorithm:
    """ABC (reference: search/search_algorithm.py:10)."""

    def next_configs(self, n: int) -> List[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        pass


class BasicVariantGenerator(SearchAlgorithm):
    """Grid expansion × random sampling (reference: basic_variant.py)."""

    def __init__(self, space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = space
        self._num_samples = num_samples
        self._rng = random.Random(seed)

    def variants(self) -> List[dict]:
        grids = list(_split_grid(self._space))
        out = []
        for _ in range(self._num_samples):
            if grids:
                paths, values = zip(*grids)
                for combo in itertools.product(*values):
                    cfg = _resolve(self._space, self._rng, {})
                    for path, val in zip(paths, combo):
                        _set_path(cfg, path, val)
                    out.append(cfg)
            else:
                out.append(_resolve(self._space, self._rng, {}))
        return out
