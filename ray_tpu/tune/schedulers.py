"""Trial schedulers: decide continue/stop/pause on every reported result.

Reference parity: python/ray/tune/schedulers/ (trial_scheduler.py:135
FIFOScheduler, async_hyperband.py ASHA, median_stopping_rule.py, pbt.py).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    # If set, the controller keeps trials within this many iterations of the
    # slowest live trial (population schedulers are meaningless when one
    # trial sprints to completion before the others start).
    pace_interval: Optional[int] = None

    def set_metric(self, metric: str, mode: str):
        self._metric = metric
        self._mode = mode
        self._sign = 1.0 if mode == "max" else -1.0

    def score(self, result: dict) -> float:
        return self._sign * result[self._metric]

    def on_trial_result(self, trial: Trial, result: dict,
                        all_trials: List[Trial]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, all_trials: List[Trial]):
        pass

    def choose_exploit(self, trial: Trial, all_trials: List[Trial]):
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.

    A trial reaching rung r (iteration = grace_period * rf^r) continues only
    if its metric is in the top 1/reduction_factor of completed rung entries.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100, brackets: int = 1):
        self._time_attr = time_attr
        self._grace = grace_period
        self._rf = reduction_factor
        self._max_t = max_t
        # Hyperband brackets: bracket s starts halving at
        # grace * rf^s (more brackets = some trials get more slack before
        # their first cut; reference: async_hyperband.py brackets arg).
        # Trials are assigned round-robin on first sight.
        self._num_brackets = max(1, brackets)
        self._bracket_levels: List[List[int]] = []
        for s in range(self._num_brackets):
            t = grace_period * (reduction_factor ** s)
            levels = []
            while t < max_t:
                levels.append(t)
                t *= reduction_factor
            self._bracket_levels.append(levels)
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0
        # (bracket, rung level) -> {trial_id: score when it crossed}
        self._rungs: Dict[tuple, Dict[str, float]] = {}

    def _bracket_of(self, trial_id: str) -> int:
        b = self._assignment.get(trial_id)
        if b is None:
            b = self._next_bracket % self._num_brackets
            self._next_bracket += 1
            self._assignment[trial_id] = b
        return b

    def _below_cutoff(self, bracket: int, level: int,
                      trial_id: str) -> bool:
        rung = self._rungs.get((bracket, level), {})
        s = rung.get(trial_id)
        if s is None or len(rung) < 2:
            return False
        k = max(1, len(rung) // self._rf)
        top_k = sorted(rung.values(), reverse=True)[:k]
        return s < top_k[-1]

    def on_trial_result(self, trial: Trial, result: dict,
                        all_trials: List[Trial]) -> str:
        if self._metric not in result:
            return CONTINUE  # warmup steps may not report the metric yet
        t = result.get(self._time_attr, trial.iteration)
        if t >= self._max_t:
            return STOP
        s = self.score(result)
        bracket = self._bracket_of(trial.trial_id)
        levels = self._bracket_levels[bracket]
        # Cross every rung level passed since the last report (time_attr may
        # advance in jumps, e.g. timesteps_total — exact equality would let
        # trials skip rungs and degrade ASHA to FIFO).
        decision = CONTINUE
        while trial.rung < len(levels) and t >= levels[trial.rung]:
            level = levels[trial.rung]
            trial.rung += 1
            rung = self._rungs.setdefault((bracket, level), {})
            rung[trial.trial_id] = s
            if self._below_cutoff(bracket, level, trial.trial_id):
                decision = STOP
        # Retroactive demotion: a trial that crossed its last rung early
        # (when the rung was near-empty, so promotion was optimistic) is
        # stopped once later arrivals push its recorded score out of the
        # top 1/rf — otherwise lockstep trials arriving weakest-first are
        # never cut and ASHA degrades to FIFO (successive-halving
        # semantics: only the top fraction of a rung is promoted).
        if decision == CONTINUE and trial.rung > 0:
            if self._below_cutoff(bracket, levels[trial.rung - 1],
                                  trial.trial_id):
                decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average falls below the median of others.

    Reference: schedulers/median_stopping_rule.py.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required

    def on_trial_result(self, trial: Trial, result: dict,
                        all_trials: List[Trial]) -> str:
        if self._metric not in result:
            return CONTINUE
        t = result.get(self._time_attr, trial.iteration)
        if t < self._grace:
            return CONTINUE
        others = []
        for other in all_trials:
            if other.trial_id == trial.trial_id:
                continue
            hist = [self.score(r) for r in other.results
                    if self._metric in r]
            if hist:
                others.append(sum(hist) / len(hist))
        if len(others) < self._min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = [self.score(r) for r in trial.results if self._metric in r]
        if not mine:
            return CONTINUE
        avg = sum(mine) / len(mine)
        return STOP if avg < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: bottom-quantile trials clone a top performer's checkpoint and
    perturb its hyperparameters (reference: schedulers/pbt.py).
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 synch: bool = True,
                 seed: Optional[int] = None):
        self._time_attr = time_attr
        self._interval = perturbation_interval
        if synch:
            self.pace_interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}

    def on_trial_result(self, trial: Trial, result: dict,
                        all_trials: List[Trial]) -> str:
        if self._metric not in result:
            return CONTINUE
        t = result.get(self._time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        scored = [(self.score(tr.last_result), tr) for tr in all_trials
                  if tr.last_result and self._metric in tr.last_result]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda x: x[0])
        n_q = max(1, int(len(scored) * self._quantile))
        bottom = [tr for _s, tr in scored[:n_q]]
        top = [tr for _s, tr in scored[-n_q:]]
        if trial in bottom and trial not in top:
            trial._exploit_target = self._rng.choice(top)  # type: ignore
            return "EXPLOIT"
        return CONTINUE

    def explore(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self._mutations.items():
            if self._rng.random() < self._resample_p or key not in out:
                if callable(spec):
                    out[key] = spec()
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                else:
                    out[key] = spec.sample(self._rng)
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(out[key], (int, float)):
                    out[key] = type(out[key])(out[key] * factor)
        return out
