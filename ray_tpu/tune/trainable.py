"""Trainables: what a trial runs.

Reference parity: python/ray/tune/trainable/trainable.py (class API) and
function_trainable.py (fn API with tune.report). Both are hosted inside one
trial actor (_TrialActor in tuner.py); class trainables step synchronously,
function trainables run in a thread and hand results over a depth-1 queue so
the function blocks until the controller has consumed the previous report
(step-wise lockstep, which schedulers need).
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional


class Trainable:
    """Subclass API: setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Dict[str, Any]):
        self.config = dict(config)
        self.training_iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]):
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return None

    def load_checkpoint(self, checkpoint: Any):
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable supports in-place config reset
        (lets PBT reuse the actor instead of restarting it)."""
        return False

    def cleanup(self):
        pass


class _Session:
    """Per-actor state backing tune.report()/tune.get_checkpoint()."""

    def __init__(self, checkpoint: Any = None):
        self.queue: queue.Queue = queue.Queue(maxsize=1)
        self.checkpoint = checkpoint
        self.last_checkpoint = checkpoint


_session: Optional[_Session] = None


def _set_session(s: Optional[_Session]):
    global _session
    _session = s


def report(metrics: Dict[str, Any], *, checkpoint: Any = None):
    """Report metrics (and optionally a checkpoint) from a fn trainable."""
    if _session is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    if checkpoint is not None:
        _session.last_checkpoint = checkpoint
    _session.queue.put(("result", dict(metrics), checkpoint))


def get_checkpoint() -> Any:
    """The checkpoint this trial was restored from (PBT exploit / resume)."""
    if _session is None:
        raise RuntimeError("tune.get_checkpoint() outside a Tune trial")
    return _session.checkpoint


class FunctionRunner:
    """Runs a user function in a thread; yields step-wise results."""

    def __init__(self, fn: Callable, config: Dict[str, Any],
                 checkpoint: Any = None):
        self._session = _Session(checkpoint)
        self._fn = fn
        self._config = dict(config)
        self._thread: Optional[threading.Thread] = None

    def _target(self):
        _set_session(self._session)
        try:
            self._fn(self._config)
            self._session.queue.put(("done", None, None))
        except BaseException:
            self._session.queue.put(("error", traceback.format_exc(), None))

    def next_result(self, timeout: Optional[float] = None):
        if self._thread is None:
            self._thread = threading.Thread(target=self._target, daemon=True)
            self._thread.start()
        try:
            return self._session.queue.get(timeout=timeout)
        except queue.Empty:
            return ("pending", None, None)

    def save(self) -> Any:
        return self._session.last_checkpoint
