"""Lazy DAG API: .bind() builds a graph, .execute() runs it.

Reference: python/ray/dag/dag_node.py:25 (DAGNode / bind / execute),
InputNode/MultiOutputNode per python/ray/dag/input_node.py,
output_node.py. Execution lowers to ordinary task/actor submissions with
ObjectRef wiring; experimental_compile() (compiled.py) lowers the same
graph onto persistent actors + mutable channels instead.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_input_node_tls = threading.local()


class ImmediateValue:
    """An already-materialized node result (workflow checkpoint replay)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal ----------------------------------------------------

    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _topo(self) -> List["DAGNode"]:
        order: List[DAGNode] = []
        seen = set()

        def visit(node: "DAGNode"):
            if id(node) in seen:
                return
            seen.add(id(node))
            for dep in node._deps():
                visit(dep)
            order.append(node)

        visit(self)
        return order

    # -- execution ----------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG with ordinary task/actor calls; returns ObjectRef(s).

        InputNode resolves to input_args[0] (or the full tuple when the
        node was indexed)."""
        results: Dict[int, Any] = {}
        for node in self._topo():
            results[id(node)] = node._execute_one(results, input_args,
                                                  input_kwargs)
        return results[id(self)]

    def _resolve(self, value, results):
        if isinstance(value, DAGNode):
            out = results[id(value)]
            # Workflow execution stores already-materialized checkpoint
            # values wrapped in ImmediateValue (workflow/api.py); unwrap
            # so they pass as plain arguments.
            if isinstance(out, ImmediateValue):
                return out.value
            return out
        return value

    def _execute_one(self, results, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self, max_message_size: int = 1 << 20,
                             channel_depth: int = 2,
                             tick_replay: bool = False):
        """Lower this graph onto pre-leased actors + reusable shm
        channels (dag/compiled.py). `channel_depth` bounds how many
        pipelined executions can be in flight at once; `tick_replay`
        arms in-place recovery (executor death -> restart + exactly-once
        replay of unacknowledged ticks instead of a typed failure)."""
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, max_message_size,
                           channel_depth=channel_depth,
                           tick_replay=tick_replay)


class InputNode(DAGNode):
    """Placeholder for the runtime input. Usable as a context manager for
    parity with the reference (`with InputNode() as inp:`)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_one(self, results, input_args, input_kwargs):
        if input_kwargs:
            # Reference semantics need InputAttributeNode for named access;
            # silently mapping kwargs to () would corrupt downstream args.
            raise ValueError(
                "DAG inputs must be positional (dag.execute(x), not "
                "dag.execute(x=...))")
        if len(input_args) == 1:
            return input_args[0]
        return input_args

    def __repr__(self):
        return "InputNode()"


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_one(self, results, input_args, input_kwargs):
        args = [self._resolve(a, results) for a in self._bound_args]
        kwargs = {k: self._resolve(v, results)
                  for k, v in self._bound_kwargs.items()}
        return self._remote_fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({self._remote_fn.__name__})"


class ClassMethodNode(DAGNode):
    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_method = actor_method

    def _execute_one(self, results, input_args, input_kwargs):
        args = [self._resolve(a, results) for a in self._bound_args]
        kwargs = {k: self._resolve(v, results)
                  for k, v in self._bound_kwargs.items()}
        return self._actor_method.remote(*args, **kwargs)

    def __repr__(self):
        return f"ClassMethodNode({self._actor_method._name})"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_one(self, results, input_args, input_kwargs):
        return [self._resolve(o, results) for o in self._bound_args]

    def __repr__(self):
        return f"MultiOutputNode({len(self._bound_args)})"
