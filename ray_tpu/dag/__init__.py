"""DAG / compiled-graph API (reference: python/ray/dag/)."""

from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                                  InputNode, MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG, DagRef
from ray_tpu.exceptions import DagExecutionError

__all__ = ["DAGNode", "InputNode", "FunctionNode", "ClassMethodNode",
           "MultiOutputNode", "CompiledDAG", "DagRef", "DagExecutionError"]
