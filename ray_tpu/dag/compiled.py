"""Compiled DAGs: pre-leased pipelines over reusable shm ring channels.

Reference: python/ray/dag/compiled_dag_node.py:141 (CompiledDAG /
CompiledTask) over the mutable-object channel layer. `compile()` pays
every control-plane cost ONCE:

  * executor actors are created (FunctionNodes) or adopted (actor
    method nodes), their placements resolved, and their worker leases
    PINNED at the hosting raylets for the DAG's lifetime (pinned
    workers are excluded from OOM victim selection and the idle reaper
    and show up in dag lease accounting until teardown);
  * every edge gets a reusable channel — a multi-slot shm ring
    (`experimental/channels.py`) when both endpoints share a node, the
    KV/object-store fallback when the DAG spans raylets; ring depth =
    pipelined ticks in flight (writer blocks when full = natural
    backpressure);
  * each participating actor is shipped ONE persistent `run_loop` task
    that reads its input channels, calls the bound methods, and writes
    downstream.

`execute()` is then one input-channel write + one output-channel read —
zero per-tick task RPCs — and `execute_async()` overlaps executions up
to the channel depth. Executor death mid-tick surfaces as a typed
`DagExecutionError` on the in-flight and all subsequent executes via a
settled-ref watcher parked on the loop refs (push, not the old 1s-slice
polling backstop); `teardown()` releases every pinned lease and unlinks
every channel segment.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                                  InputNode, MultiOutputNode)
from ray_tpu.exceptions import DagExecutionError
from ray_tpu.experimental.channel import ChannelClosedError
from ray_tpu.experimental.channels import RingChannel, StoreChannel

# How long a run loop waits on one read before re-checking channel
# liveness; the read itself raises ChannelClosedError on close/orphan.
_LOOP_READ_TIMEOUT_S = None


class _DagError:
    """Error marker shipped through a channel; re-raised at the consumer."""

    def __init__(self, error: Exception):
        self.error = error


def _run_compiled_loop(fns: List, node_specs: List[tuple]):
    """One executor loop driving one or more compiled nodes.

    node_specs[i] = (in_readers, arg_template, kw_template, out_writer)
    for fns[i], in topological order — intra-executor edges resolve
    because the producer wrote its ring slot earlier in the same pass
    and this node holds its own reader cursor on that channel.
    """
    writers = [spec[3] for spec in node_specs]

    def _close_all():
        for w in writers:
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown race
                pass

    while True:
        closed = False
        for fn, (in_readers, arg_t, kw_t, out_writer) in zip(fns,
                                                             node_specs):
            if closed:
                continue
            values = []
            try:
                for r in in_readers:
                    values.append(r.read(timeout=_LOOP_READ_TIMEOUT_S))
            except ChannelClosedError:
                _close_all()
                closed = True
                continue
            except Exception as e:  # noqa: BLE001 — a read error must
                # surface to the caller as a typed result, never kill the
                # loop silently: a dead loop leaves every later execute()
                # spinning on an output channel nobody will write.
                try:
                    out_writer.write(_DagError(e))
                except ChannelClosedError:
                    _close_all()
                    closed = True
                continue
            err = next((v for v in values if isinstance(v, _DagError)),
                       None)
            if err is not None:
                result = err
            else:
                args = [values[i] if kind == "chan" else const
                        for kind, i, const in arg_t]
                kwargs = {key: (values[i] if kind == "chan" else const)
                          for key, kind, i, const in kw_t}
                try:
                    result = fn(*args, **kwargs)
                except Exception as e:  # noqa: BLE001
                    result = _DagError(e)
            try:
                out_writer.write(result)
            except ChannelClosedError:
                _close_all()
                closed = True
        if closed:
            return "closed"


def _dag_loop_method(self, method_names: List[str], node_specs: List[tuple]):
    """Injected onto every actor instance (core_worker instantiation) so a
    compiled DAG can pin a loop to a user actor without the class opting
    in (reference: aDAG's internal actor executables)."""
    return _run_compiled_loop([getattr(self, m) for m in method_names],
                              node_specs)


_EXECUTOR_OPTION_KEYS = ("num_cpus", "num_tpus", "num_gpus", "resources",
                         "scheduling_strategy", "runtime_env")

_DRIVER = "__driver__"

_tick_hist = None
_inflight_gauge = None


def _metric_handles():
    global _tick_hist, _inflight_gauge
    if _tick_hist is None:
        from ray_tpu.util import metrics
        _tick_hist = metrics.Histogram(
            "ray_tpu_dag_tick_seconds",
            "compiled-DAG per-tick latency (input write -> output read)",
            boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                        0.01, 0.025, 0.05, 0.1, 0.5, 1.0, 5.0])
        _inflight_gauge = metrics.Gauge(
            "ray_tpu_dag_inflight_executions",
            "compiled-DAG executions submitted but not yet collected")
    return _tick_hist, _inflight_gauge


class CompiledDAG:
    """Compile once, tick forever. See module docstring.

    Lifecycle: `compile()` (or `dag.experimental_compile()`) acquires
    channels + pinned leases + run loops; `execute()` /
    `execute_async()` tick; `teardown()` releases everything —
    scripts/check_dag_teardown.py statically enforces that every
    acquisition has a release on the teardown AND the compile-error
    path.
    """

    @classmethod
    def compile(cls, dag: DAGNode, *, channel_depth: int = 2,
                max_message_size: int = 1 << 20,
                compile_timeout_s: float = 60.0) -> "CompiledDAG":
        return cls(dag, max_message_size, channel_depth=channel_depth,
                   compile_timeout_s=compile_timeout_s)

    def __init__(self, root: DAGNode, max_message_size: int = 1 << 20,
                 channel_depth: int = 2, compile_timeout_s: float = 60.0):
        self._root = root
        self._max_size = max_message_size
        self._depth = max(1, int(channel_depth))
        self._dag_id = os.urandom(6).hex()
        # Resource registries — initialized FIRST so teardown() is safe
        # from any partial-compile state.
        self._channels: List[Any] = []          # every created channel
        self._loop_refs: List[Any] = []
        self._executor_actors: List[Any] = []
        self._pinned_raylets: List[str] = []
        self._input_writers: List[Any] = []
        self._output_readers: List[Any] = []
        self._watcher = None
        self._torn_down = False
        self._error: Optional[BaseException] = None
        self._submit_lock = threading.Lock()
        self._collect_lock = threading.Lock()
        self._next_seq = 0
        self._collected = 0
        self._results: Dict[int, list] = {}
        # Per-tick output-read resume state: values already drained from
        # SOME output readers when a timeout interrupted the rest. The
        # cursors of the drained readers advanced persistently, so a
        # retrying collect must resume from here — re-reading would pair
        # tick N+1's value from one reader with tick N's from another.
        self._tick_buf: Dict[int, Any] = {}
        self._submit_ts: Dict[int, float] = {}
        self._inflight = 0
        self.max_inflight = 0
        self.ticks = 0
        try:
            t0 = time.time()
            self._compile(compile_timeout_s)
            self._export_span("dag:compile", t0, time.time())
        except BaseException:
            # Error-path release: whatever the partial compile acquired
            # (channels, leases, executor actors) must not leak.
            self.teardown()
            raise

    # ------------------------------------------------------------------
    # Compilation
    # ------------------------------------------------------------------
    def _compile(self, compile_timeout_s: float):
        from ray_tpu._private import worker_api

        root = self._root
        nodes = root._topo()
        multi = isinstance(root, MultiOutputNode)
        compute_nodes: List[DAGNode] = []
        for node in nodes:
            if isinstance(node, InputNode):
                continue
            if isinstance(node, (FunctionNode, ClassMethodNode)):
                compute_nodes.append(node)
            elif isinstance(node, MultiOutputNode):
                if node is not root:
                    raise ValueError("MultiOutputNode must be the DAG root")
            else:
                raise TypeError(f"cannot compile node {node!r}")
        outputs = (list(root._bound_args) if multi else [root])
        for o in outputs:
            if not isinstance(o, (FunctionNode, ClassMethodNode)):
                raise TypeError("DAG outputs must be compute nodes")

        # 1. One executor actor per FunctionNode; ClassMethodNodes adopt
        # their user actor. All nodes of one actor share a single loop.
        owner_of: Dict[int, Any] = {}          # id(node) -> actor handle
        for node in compute_nodes:
            if isinstance(node, FunctionNode):
                opts = {k: v for k, v in node._remote_fn._options.items()
                        if k in _EXECUTOR_OPTION_KEYS}
                executor = _executor_actor_class().options(
                    max_concurrency=1, **opts).remote(
                        node._remote_fn._function)
                self._executor_actors.append(executor)
                owner_of[id(node)] = executor
            else:
                owner_of[id(node)] = node._actor_method._handle

        # 2. Pin every participant's lease ONCE; placements come back
        # with node ids, which drive the per-edge channel choice.
        core = worker_api.get_core()
        handles = {h._actor_id: h for h in owner_of.values()}
        placements = worker_api._call_on_core_loop(
            core, core.dag_pin_actors(self._dag_id, list(handles),
                                      timeout_s=compile_timeout_s),
            compile_timeout_s)
        self._pinned_raylets = sorted(
            {p["raylet"] for p in placements.values()})
        driver_node = worker_api._call_on_core_loop(
            core, core.local_node_id(), 30)

        def node_of(entity) -> Any:
            if entity == _DRIVER:
                return driver_node
            return placements[entity]["node_id"]

        def entity_of(node: DAGNode) -> Any:
            return owner_of[id(node)]._actor_id

        # 3. Edges: which NODES consume each produced value. Reader
        # cursors are per consuming node (two nodes on one actor each
        # hold their own cursor — a shared one would double-advance per
        # tick); a node binding the same upstream twice (diamond) still
        # collapses onto one cursor below. The input channel's consumers
        # are every node reading InputNode plus const-only nodes (the
        # input is their tick trigger — a triggerless loop would spin
        # hot and never observe teardown).
        consumers: Dict[int, List[DAGNode]] = {id(n): [] for n in nodes}
        input_consumers: List[DAGNode] = []
        for node in compute_nodes:
            deps = node._deps()
            if not deps or any(isinstance(d, InputNode) for d in deps):
                input_consumers.append(node)
            for dep in deps:
                if not isinstance(dep, InputNode):
                    consumers[id(dep)].append(node)

        # 4. Create the channels. One producer each: the driver for the
        # input channel, a node's hosting actor otherwise. A ring needs
        # every endpoint on ONE node; any remote endpoint moves the whole
        # edge to the KV/store fallback.
        ch_index = 0

        def place_of(consumer) -> Any:
            if consumer is _DRIVER:
                return driver_node
            return node_of(entity_of(consumer))

        def make_channel(writer_place, reader_list):
            nonlocal ch_index
            places = {writer_place}
            places.update(place_of(r) for r in reader_list)
            if len(places) == 1 and None not in places:
                ch = RingChannel(self._max_size, self._depth,
                                 len(reader_list))
            else:
                ch = StoreChannel(f"{self._dag_id}/{ch_index}",
                                  self._depth, len(reader_list))
            ch_index += 1
            self._channels.append(ch)
            return ch

        def dedup(seq):
            out, seen = [], set()
            for x in seq:
                if id(x) not in seen:
                    seen.add(id(x))
                    out.append(x)
            return out

        input_nodes_list = dedup(input_consumers)
        input_channel = make_channel(driver_node, input_nodes_list)
        input_reader_of = {id(n): input_channel.reader(i)
                           for i, n in enumerate(input_nodes_list)}
        out_channel_of: Dict[int, Any] = {}
        reader_of: Dict[Tuple[int, int], Any] = {}
        driver_readers: Dict[int, Any] = {}
        for node in compute_nodes:
            readers = dedup(consumers[id(node)])
            if node in outputs:
                readers = readers + [_DRIVER]
            ch = make_channel(place_of(node), readers)
            out_channel_of[id(node)] = ch
            for i, consumer in enumerate(readers):
                if consumer is _DRIVER:
                    driver_readers[id(node)] = ch.reader(i)
                else:
                    reader_of[(id(node), id(consumer))] = ch.reader(i)

        # 5. Node specs: per consumed value either a channel-read index
        # or an inline constant; repeat reads collapse onto one reader.
        def node_spec(node: DAGNode) -> tuple:
            in_readers: List[Any] = []
            reader_idx: Dict[Any, int] = {}

            def wire(value):
                if isinstance(value, InputNode):
                    key, rd = "input", input_reader_of[id(node)]
                elif isinstance(value, DAGNode):
                    key, rd = id(value), reader_of[(id(value), id(node))]
                else:
                    return ("const", -1, value)
                if key not in reader_idx:
                    reader_idx[key] = len(in_readers)
                    in_readers.append(rd)
                return ("chan", reader_idx[key], None)

            arg_t = [wire(a) for a in node._bound_args]
            kw_t = []
            for k, v in node._bound_kwargs.items():
                kind, i, const = wire(v)
                kw_t.append((k, kind, i, const))
            if not in_readers:
                in_readers.append(input_reader_of[id(node)])
            writer = out_channel_of[id(node)]
            if isinstance(writer, RingChannel):
                writer = writer.writer()
            return (in_readers, arg_t, kw_t, writer)

        # 6. Ship ONE run loop per actor (an actor's nodes share it —
        # separate loops would deadlock on the actor's concurrency slot).
        groups: Dict[Any, Tuple[Any, List[DAGNode]]] = {}
        for node in compute_nodes:
            handle = owner_of[id(node)]
            groups.setdefault(handle._actor_id, (handle, []))[1].append(node)
        for handle, group_nodes in groups.values():
            specs = [node_spec(n) for n in group_nodes]
            if isinstance(group_nodes[0], FunctionNode):
                self._loop_refs.append(handle.run_loop.remote(specs))
            else:
                from ray_tpu.actor import ActorMethod
                loop_method = ActorMethod(handle, "__ray_tpu_dag_loop__")
                self._loop_refs.append(loop_method.remote(
                    [n._actor_method._name for n in group_nodes], specs))

        # 7. Driver endpoints + the settled-ref failure watcher.
        self._input_writers = [input_channel]
        self._output_readers = [driver_readers[id(o)] for o in outputs]
        self._multi = multi
        self._arm_watcher(core)

    # ------------------------------------------------------------------
    # Failure watcher: push-based, parked on the loop refs
    # ------------------------------------------------------------------
    def _arm_watcher(self, core):
        import asyncio

        refs = list(self._loop_refs)

        async def _watch():
            # Any settled loop ref before teardown = dead executor: the
            # loops only return once their channels close. get() digs
            # out the cause (ActorDiedError / WorkerCrashedError / app
            # failure in the loop plumbing).
            done, _ = await core.wait_async(refs, num_returns=1,
                                            timeout=None, fetch_local=False)
            try:
                await core.get_async([done[0]], 5)
                return RuntimeError("executor loop exited before teardown")
            except Exception as e:  # noqa: BLE001
                return e

        fut = asyncio.run_coroutine_threadsafe(_watch(), core.loop)

        def _on_done(f):
            if f.cancelled() or self._torn_down:
                return
            try:
                cause = f.result()
            except Exception as e:  # noqa: BLE001
                cause = e
            self._fail(DagExecutionError(
                "compiled DAG executor died mid-tick", cause))

        fut.add_done_callback(_on_done)
        self._watcher = fut

    def _fail(self, err: DagExecutionError):
        """Mark the DAG failed and wake EVERY blocked channel end: the
        in-flight execute raises typed instead of wedging, and so does
        every subsequent one."""
        if self._error is None:
            self._error = err
        for ch in self._channels:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — teardown race
                pass

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def execute(self, *args, timeout: Optional[float] = None) -> Any:
        """One pipeline tick, synchronously: channel write + read."""
        return self.execute_async(*args).result(timeout)

    def execute_async(self, *args) -> "DagRef":
        """Submit a tick without waiting for its output: overlapping
        executions are bounded by the channel depth (the input write
        blocks once `depth` ticks are in flight — backpressure, not an
        error). A single-threaded caller must therefore collect results
        at least every `channel_depth` submissions (see
        StagePipeline.run for the windowed pattern); submitting
        unboundedly ahead would block this write with nobody draining
        the output rings."""
        self._check_live()
        value = args[0] if len(args) == 1 else args
        with self._submit_lock:
            self._check_live()
            try:
                for w in self._input_writers:
                    w.write(value)
            except ChannelClosedError:
                self._raise_dead()
            seq = self._next_seq
            self._next_seq += 1
            self._submit_ts[seq] = time.time()
            self._inflight += 1
            self.max_inflight = max(self.max_inflight, self._inflight)
            try:
                _, gauge = _metric_handles()
                gauge.set(float(self._inflight))
            except Exception:  # noqa: BLE001 — metrics never block ticks
                pass
        return DagRef(self, seq)

    def _collect(self, seq: int, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._collect_lock:
            if seq < self._collected and seq not in self._results:
                raise ValueError(
                    f"DagRef for tick {seq} was already consumed — "
                    f"result() is one-shot")
            while seq not in self._results:
                if self._error is not None:
                    raise self._error
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                outs = []
                try:
                    # Drain EVERY output before the tick completes (an
                    # unread channel would hand this tick's value to the
                    # next collect); the same node bound twice in a
                    # MultiOutputNode shares one reader — read it once.
                    # Reads resume from _tick_buf after a timeout (their
                    # cursors advanced persistently), and copy=True
                    # detaches results from the ring slots the writer
                    # will recycle `depth` ticks from now — callers may
                    # hold results indefinitely.
                    for r in self._output_readers:
                        if id(r) not in self._tick_buf:
                            self._tick_buf[id(r)] = r.read(
                                timeout=remaining, copy=True)
                        outs.append(self._tick_buf[id(r)])
                    self._tick_buf.clear()
                except ChannelClosedError:
                    self._raise_dead()
                done_seq = self._collected
                self._collected += 1
                self._results[done_seq] = outs
                self._inflight -= 1
                self.ticks += 1
                t0 = self._submit_ts.pop(done_seq, None)
                now = time.time()
                try:
                    hist, gauge = _metric_handles()
                    if t0 is not None:
                        hist.observe(now - t0)
                    gauge.set(float(self._inflight))
                except Exception:  # noqa: BLE001
                    pass
                if t0 is not None:
                    self._export_span("dag:tick", t0, now,
                                      only_if_traced=True)
            outs = self._results.pop(seq)
        err = next((o for o in outs if isinstance(o, _DagError)), None)
        if err is not None:
            raise err.error
        return outs if len(outs) > 1 else outs[0]

    def _check_live(self):
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._error is not None:
            raise self._error

    def _raise_dead(self):
        if self._error is not None:
            raise self._error
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        raise DagExecutionError("compiled DAG channel closed unexpectedly")

    def stats(self) -> dict:
        return {"dag_id": self._dag_id, "ticks": self.ticks,
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "channels": len(self._channels),
                "pinned_raylets": list(self._pinned_raylets)}

    # ------------------------------------------------------------------
    # Teardown
    # ------------------------------------------------------------------
    def teardown(self):
        """Release every compile-time acquisition: close channels (run
        loops exit), await the loops, release pinned leases, kill
        executor actors, unlink every shm segment / KV record."""
        if self._torn_down:
            return
        self._torn_down = True
        if self._watcher is not None:
            self._watcher.cancel()
        import ray_tpu
        # Close BEFORE waiting: a loop blocked mid-read anywhere in the
        # pipeline only exits once its channels wake it.
        for ch in self._channels:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:  # noqa: BLE001 — dead executor: lease died
                pass
        try:
            from ray_tpu._private import worker_api
            core = worker_api.peek_core()
            if core is not None and self._pinned_raylets:
                worker_api._call_on_core_loop(
                    core, core.dag_release(self._dag_id,
                                           self._pinned_raylets), 30)
        except Exception:  # noqa: BLE001 — cluster already down
            pass
        for a in self._executor_actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        for ch in self._channels:
            try:
                ch.destroy()
            except Exception:  # noqa: BLE001
                pass
        try:
            _, gauge = _metric_handles()
            gauge.set(0.0)
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _export_span(self, name: str, start: float, end: float,
                     only_if_traced: bool = False):
        try:
            from ray_tpu.util import tracing
            if only_if_traced and not tracing.is_enabled():
                return
            from ray_tpu._private import flightrec
            tracing.export_span(flightrec.span_event(
                name, f"dag:{self._dag_id}", start, end))
        except Exception:  # noqa: BLE001 — observability never blocks
            pass


class DagRef:
    """Handle to one submitted tick; `result()` blocks for its outputs.
    Outputs complete strictly in submission order (the pipeline is
    FIFO), so collecting a later ref first also drains earlier ones."""

    __slots__ = ("_dag", "_seq")

    def __init__(self, dag: CompiledDAG, seq: int):
        self._dag = dag
        self._seq = seq

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._dag._collect(self._seq, timeout)

    def done(self) -> bool:
        return self._seq in self._dag._results \
            or self._seq < self._dag._collected


_executor_cls = None


def _executor_actor_class():
    """Defers the @remote wrapping until first use (import order)."""
    global _executor_cls
    if _executor_cls is None:
        import ray_tpu

        @ray_tpu.remote
        class _DAGExecutor:
            """Hosts FunctionNode loops (reference: CompiledTask worker)."""

            def __init__(self, fn):
                self._fn = fn

            def run_loop(self, node_specs):
                return _run_compiled_loop([self._fn] * len(node_specs),
                                          node_specs)

        _executor_cls = _DAGExecutor
    return _executor_cls
