"""Compiled DAGs: pre-leased pipelines over reusable shm ring channels.

Reference: python/ray/dag/compiled_dag_node.py:141 (CompiledDAG /
CompiledTask) over the mutable-object channel layer. `compile()` pays
every control-plane cost ONCE:

  * executor actors are created (FunctionNodes) or adopted (actor
    method nodes), their placements resolved, and their worker leases
    PINNED at the hosting raylets for the DAG's lifetime (pinned
    workers are excluded from OOM victim selection and the idle reaper
    and show up in dag lease accounting until teardown);
  * every edge gets a reusable channel — a multi-slot shm ring
    (`experimental/channels.py`) when both endpoints share a node, the
    KV/object-store fallback when the DAG spans raylets; ring depth =
    pipelined ticks in flight (writer blocks when full = natural
    backpressure);
  * each participating actor is shipped ONE persistent `run_loop` task
    that reads its input channels, calls the bound methods, and writes
    downstream.

`execute()` is then one input-channel write + one output-channel read —
zero per-tick task RPCs — and `execute_async()` overlaps executions up
to the channel depth.

Self-healing (PR 13): every channel message carries the DAG's monotonic
tick sequence. On a `tick_replay=True` DAG, executor death no longer
poisons the pipeline: the settled-ref watcher transitions the DAG to
RECOVERING instead of failing it — only the dead participant(s) are
restarted (FunctionNode executors are recreated by the DAG; user actors
ride their own `max_restarts` / preemption-migration machinery), their
worker leases are re-pinned at the hosting raylets, only the channels
whose locality changed are re-created (surviving ring segments — and
the reader cursors persisted inside them — are kept and reopened), the
persistent run loops are re-shipped, and the driver replays every
unacknowledged tick from a bounded replay buffer. Surviving executors
dedupe by sequence (skip recompute, re-emit their cached result only
onto edges that lost data), so a tick that partially crossed the
pipeline completes exactly once and survivors keep their pids. A
node/gang drain notice triggers the same machinery *proactively*: the
affected executors are migrated (uncharged, `preempted_restarts`),
channels re-homed (ring<->store as locality changes) and the dying
members' pins released BEFORE the kill. Non-replayable DAGs keep the
typed fail-fast `DagExecutionError`; `teardown()` releases every pinned
lease and unlinks every channel segment on every path.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                                  InputNode, MultiOutputNode)
from ray_tpu.exceptions import DagExecutionError, DagRecoveryError
from ray_tpu.experimental.channel import ChannelClosedError
from ray_tpu.experimental.channels import RingChannel, StoreChannel

# How long a run loop waits on one read before re-checking channel
# liveness; the read itself raises ChannelClosedError on close/orphan.
_LOOP_READ_TIMEOUT_S = None


class _DagError:
    """Error marker shipped through a channel; re-raised at the consumer."""

    def __init__(self, error: Exception):
        self.error = error


class _Unrecoverable(Exception):
    """Internal: recovery cannot possibly succeed (participant dead for
    good); carries the typed error to surface."""

    def __init__(self, error: BaseException):
        super().__init__(str(error))
        self.error = error


def _wire_bytes(message) -> bytes:
    """Serialize a (seq, value) message into the channel wire format as
    PRIVATE bytes — safe to cache for a recovery resend, where a live
    result object could alias a zero-copy view onto a ring slot the
    writer has since recycled."""
    from ray_tpu._private.serialization import context_for_process
    return context_for_process().serialize(message).to_bytes()


def _run_compiled_loop(fns: List, node_specs: List[tuple],
                       node_keys: Optional[List[int]] = None,
                       state: Optional[dict] = None,
                       resume: Optional[dict] = None,
                       cache_bound: int = 64,
                       detach: bool = False):
    """One executor loop driving one or more compiled nodes.

    node_specs[i] = (in_readers, arg_template, kw_template, out_writer)
    for fns[i], in topological order — intra-executor edges resolve
    because the producer wrote its ring slot earlier in the same pass
    and this node holds its own reader cursor on that channel.

    Messages are (tick_seq, value) pairs. `state` is the actor-resident
    per-node recovery state ({node_key: {last, cache, stash, careful}})
    that survives loop re-ships on a surviving executor: `last` is the
    newest tick this node computed (the exactly-once dedupe floor),
    `cache` its recent results as PRIVATE wire bytes (the resend
    source; kept only when `detach` — recovery — is armed), `stash`
    per-reader ahead-of-target values. `resume[node_key]` directives ship with a recovery re-ship:
    `start` floors a fresh node at the replay floor, `resend_from`
    makes a survivor re-emit its cached tail onto an edge that lost
    data, `careful` forces copied (never zero-copy) reads for the
    post-recovery window where out-of-order deliveries can be stashed
    past their ring slot's lifetime.
    """
    n = len(fns)
    if node_keys is None:
        node_keys = list(range(n))
    if state is None:
        state = {}
    resume = resume or {}
    writers = [spec[3] for spec in node_specs]

    def _close_all():
        for w in writers:
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown race
                pass

    sts = []
    for i, key in enumerate(node_keys):
        readers = node_specs[i][0]
        st = state.get(key)
        if st is None or len(st.get("stash", ())) != len(readers):
            st = {"last": -1, "cache": OrderedDict(),
                  "stash": [dict() for _ in readers], "careful": 0}
            state[key] = st
        sts.append(st)

    # Resume directives: floor fresh nodes at the replay start, then
    # re-emit cached tails onto edges whose contents were lost (the
    # channel was re-created/re-homed, or a downstream reader was
    # restarted and its consumed-but-unprocessed ticks died with it).
    for i, key in enumerate(node_keys):
        d = resume.get(key) or {}
        st = sts[i]
        if st["last"] < 0:
            st["last"] = int(d.get("start", 0)) - 1
        st["careful"] = max(st.get("careful", 0), int(d.get("careful", 0)))
        rf = d.get("resend_from")
        if rf is not None:
            # Store channels re-seal dangling oversize records in place
            # (a dead writer's object refs) before appending the replay;
            # ring channels have no persisted records to repair.
            resend = getattr(writers[i], "resend_bytes",
                             writers[i].write_bytes)
            for seq in range(int(rf), st["last"] + 1):
                if seq in st["cache"]:
                    try:
                        resend(st["cache"][seq])
                    except ChannelClosedError:
                        _close_all()
                        return "closed"

    def _fill(st: dict, readers: List) -> tuple:
        """Block until every reader holds this node's next tick; returns
        (seq, values). Duplicate deliveries (replays) are dropped by
        seq; ahead-of-target deliveries are stashed — copied out of the
        ring while in the careful window, since a stashed zero-copy
        view could be lapped by the writer before it is consumed."""
        want = st["last"] + 1
        for j, r in enumerate(readers):
            stash = st["stash"][j]
            for stale in [s for s in stash if s < want]:
                del stash[s]
            while want not in stash:
                seq, val = r.read(timeout=_LOOP_READ_TIMEOUT_S,
                                  copy=st["careful"] > 0)
                if seq >= want:
                    stash[seq] = val
        return want, [st["stash"][j].pop(want) for j in range(len(readers))]

    while True:
        for i, (fn, (in_readers, arg_t, kw_t, out_writer)) in \
                enumerate(zip(fns, node_specs)):
            st = sts[i]
            try:
                seq, values = _fill(st, in_readers)
            except ChannelClosedError:
                _close_all()
                return "closed"
            except Exception as e:  # noqa: BLE001 — a read error must
                # surface to the caller as a typed result, never kill the
                # loop silently: a dead loop leaves every later execute()
                # spinning on an output channel nobody will write.
                seq = st["last"] + 1
                values = None
                result = _DagError(e)
            if values is not None:
                err = next((v for v in values if isinstance(v, _DagError)),
                           None)
                if err is not None:
                    result = err
                else:
                    args = [values[j] if kind == "chan" else const
                            for kind, j, const in arg_t]
                    kwargs = {key: (values[j] if kind == "chan" else const)
                              for key, kind, j, const in kw_t}
                    try:
                        result = fn(*args, **kwargs)
                    except Exception as e:  # noqa: BLE001
                        result = _DagError(e)
            st["last"] = seq
            if st["careful"] > 0:
                st["careful"] -= 1
            try:
                if detach:
                    # Recovery armed: serialize ONCE, cache the private
                    # wire bytes (a live result could alias a zero-copy
                    # view onto a ring slot the upstream writer recycles
                    # `depth` ticks from now — resending it later would
                    # replay silently corrupted memory), write the same
                    # bytes downstream.
                    wire = _wire_bytes((seq, result))
                    st["cache"][seq] = wire
                    while len(st["cache"]) > cache_bound:
                        st["cache"].popitem(last=False)
                    out_writer.write_bytes(wire)
                else:
                    # Fail-fast DAGs never resend: skip the cache.
                    out_writer.write((seq, result))
            except ChannelClosedError:
                _close_all()
                return "closed"


def _dag_loop_method(self, method_names: List[str], node_specs: List[tuple],
                     node_keys: Optional[List[int]] = None,
                     resume: Optional[dict] = None, cache_bound: int = 64,
                     dag_id: str = "", detach: bool = False):
    """Injected onto every actor instance (core_worker instantiation) so a
    compiled DAG can pin a loop to a user actor without the class opting
    in (reference: aDAG's internal actor executables). The per-dag
    recovery state rides the instance so a surviving actor keeps its
    dedupe cache across loop re-ships (a restarted instance starts
    fresh — exactly the semantics recovery wants)."""
    root = self.__dict__.setdefault("__ray_tpu_dag_state__", {})
    return _run_compiled_loop([getattr(self, m) for m in method_names],
                              node_specs, node_keys,
                              root.setdefault(dag_id, {}), resume,
                              cache_bound, detach)


_EXECUTOR_OPTION_KEYS = ("num_cpus", "num_tpus", "num_gpus", "resources",
                         "scheduling_strategy", "runtime_env")

_DRIVER = -1          # reader-entity key for the driver endpoint

_metrics = None


def _metric_handles() -> dict:
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics
        _metrics = {
            "tick": metrics.Histogram(
                "ray_tpu_dag_tick_seconds",
                "compiled-DAG per-tick latency (input write -> output read)",
                boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                            0.01, 0.025, 0.05, 0.1, 0.5, 1.0, 5.0]),
            "inflight": metrics.Gauge(
                "ray_tpu_dag_inflight_executions",
                "compiled-DAG executions submitted but not yet collected"),
            "recoveries": metrics.Counter(
                "ray_tpu_dag_recoveries_total",
                "compiled-DAG in-place recoveries (executor death or "
                "proactive drain migration) that returned the DAG to "
                "RUNNING"),
            "recovery_s": metrics.Histogram(
                "ray_tpu_dag_recovery_seconds",
                "compiled-DAG recovery latency (failure/notice -> RUNNING)",
                boundaries=[0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                            30.0, 60.0]),
            "replayed": metrics.Counter(
                "ray_tpu_dag_replayed_ticks_total",
                "unacknowledged ticks replayed from the driver-side "
                "buffer after a compiled-DAG recovery"),
        }
    return _metrics


class _Participant:
    """One loop-hosting actor: a DAG-owned FunctionNode executor or an
    adopted user actor, plus the node group its single loop drives."""

    __slots__ = ("handle", "node_keys", "is_fn", "fn", "fn_opts",
                 "loop_ref")

    def __init__(self, handle, node_keys, is_fn, fn=None, fn_opts=None):
        self.handle = handle
        self.node_keys = list(node_keys)
        self.is_fn = is_fn
        self.fn = fn
        self.fn_opts = fn_opts or {}
        self.loop_ref = None


class CompiledDAG:
    """Compile once, tick forever. See module docstring.

    Lifecycle: `compile()` (or `dag.experimental_compile()`) acquires
    channels + pinned leases + run loops; `execute()` /
    `execute_async()` tick; executor death on a `tick_replay` DAG runs
    recompile-in-place recovery (`_recover`); `teardown()` releases
    everything — scripts/check_dag_teardown.py statically enforces that
    every acquisition has a release on the teardown, compile-error AND
    recovery-failure paths.
    """

    @classmethod
    def compile(cls, dag: DAGNode, *, channel_depth: int = 2,
                max_message_size: int = 1 << 20,
                compile_timeout_s: float = 60.0,
                tick_replay: bool = False,
                recovery_timeout_s: float = 60.0,
                max_recoveries: int = 64,
                patient_readers: bool = False) -> "CompiledDAG":
        return cls(dag, max_message_size, channel_depth=channel_depth,
                   compile_timeout_s=compile_timeout_s,
                   tick_replay=tick_replay,
                   recovery_timeout_s=recovery_timeout_s,
                   max_recoveries=max_recoveries,
                   patient_readers=patient_readers)

    def __init__(self, root: DAGNode, max_message_size: int = 1 << 20,
                 channel_depth: int = 2, compile_timeout_s: float = 60.0,
                 tick_replay: bool = False,
                 recovery_timeout_s: float = 60.0,
                 max_recoveries: int = 64,
                 patient_readers: bool = False):
        self._root = root
        self._max_size = max_message_size
        self._depth = max(1, int(channel_depth))
        self._dag_id = os.urandom(6).hex()
        self._tick_replay = bool(tick_replay)
        # Patient channel readers nap instead of hot-polling: set this
        # when node compute is ms-scale per tick (RL rollouts, learn
        # steps) so blocked readers don't starve computing peers on
        # small boxes; leave False for µs-tick pipelines (hot wakes).
        self._patient = bool(patient_readers)
        self._recovery_timeout_s = float(recovery_timeout_s)
        self._max_recoveries = int(max_recoveries)
        # Resource registries — initialized FIRST so teardown() is safe
        # from any partial-compile state.
        self._channels: List[Any] = []          # every live channel
        self._edge_channels: Dict[Any, Any] = {}
        self._loop_refs: List[Any] = []
        self._executor_actors: List[Any] = []
        self._participants: List[_Participant] = []
        self._placements: Dict[Any, dict] = {}
        self._pinned_raylets: List[str] = []
        self._input_writers: List[Any] = []
        self._output_readers: List[Any] = []
        self._output_map: List[int] = []
        self._out_stash: Dict[int, dict] = {}
        self._watcher = None
        self._watch_epoch = 0
        self._epoch = 0
        self._driver_node = None
        self._torn_down = False
        self._error: Optional[BaseException] = None
        self._state = "running"
        self._recovered_evt = threading.Event()
        self._recovered_evt.set()
        self._recover_lock = threading.Lock()
        self._migration_inflight = False
        self._drain_cb = None
        self._drain_seen = 0
        self._submit_lock = threading.Lock()
        self._collect_lock = threading.Lock()
        self._next_seq = 0
        self._collected = 0
        self._results: Dict[int, list] = {}
        self._replay: Dict[int, Any] = {}
        self._submit_ts: Dict[int, float] = {}
        self._inflight = 0
        self.max_inflight = 0
        self.ticks = 0
        self.recoveries = 0
        self.replayed_ticks = 0
        try:
            t0 = time.time()
            self._compile(compile_timeout_s)
            self._export_span("dag:compile", t0, time.time())
        except BaseException:
            # Error-path release: whatever the partial compile acquired
            # (channels, leases, executor actors) must not leak.
            self.teardown()
            raise

    # ------------------------------------------------------------------
    # Compilation
    # ------------------------------------------------------------------
    def _compile(self, compile_timeout_s: float):
        from ray_tpu._private import worker_api

        self._build_graph()
        self._create_participants()
        core = worker_api.get_core()
        self._pin([p.handle._actor_id for p in self._participants],
                  compile_timeout_s)
        for edge in self._edge_defs:
            self._make_edge_channel(edge)
        self._ship_loops({})
        self._refresh_driver_endpoints()
        self._arm_watcher(core)
        self._register_drain_listener()

    def _build_graph(self):
        """Topology metadata, built once — recovery re-derives channels
        and specs from it without re-walking the user graph."""
        root = self._root
        nodes = root._topo()
        multi = isinstance(root, MultiOutputNode)
        compute_nodes: List[DAGNode] = []
        for node in nodes:
            if isinstance(node, InputNode):
                continue
            if isinstance(node, (FunctionNode, ClassMethodNode)):
                compute_nodes.append(node)
            elif isinstance(node, MultiOutputNode):
                if node is not root:
                    raise ValueError("MultiOutputNode must be the DAG root")
            else:
                raise TypeError(f"cannot compile node {node!r}")
        outputs = (list(root._bound_args) if multi else [root])
        for o in outputs:
            if not isinstance(o, (FunctionNode, ClassMethodNode)):
                raise TypeError("DAG outputs must be compute nodes")
        self._compute_nodes = compute_nodes
        self._outputs = outputs
        self._multi = multi
        self._key_of = {id(n): i for i, n in enumerate(compute_nodes)}

        # Edges: which NODES consume each produced value. Reader cursors
        # are per consuming node (two nodes on one actor each hold their
        # own cursor — a shared one would double-advance per tick); a
        # node binding the same upstream twice (diamond) still collapses
        # onto one cursor in _node_spec. The input channel's consumers
        # are every node reading InputNode plus const-only nodes (the
        # input is their tick trigger — a triggerless loop would spin
        # hot and never observe teardown).
        consumers: Dict[int, List[int]] = {i: []
                                           for i in range(len(compute_nodes))}
        input_consumers: List[int] = []
        for node in compute_nodes:
            k = self._key_of[id(node)]
            deps = node._deps()
            if not deps or any(isinstance(d, InputNode) for d in deps):
                input_consumers.append(k)
            for dep in deps:
                if not isinstance(dep, InputNode):
                    consumers[self._key_of[id(dep)]].append(k)

        def dedup(seq):
            out, seen = [], set()
            for x in seq:
                if x not in seen:
                    seen.add(x)
                    out.append(x)
            return out

        output_keys = {self._key_of[id(o)] for o in outputs}
        self._edge_defs: List[dict] = [
            {"key": "input", "writer": None, "readers": dedup(input_consumers)}
        ]
        for k in range(len(compute_nodes)):
            readers = dedup(consumers[k])
            if k in output_keys:
                readers = readers + [_DRIVER]
            self._edge_defs.append({"key": k, "writer": k,
                                    "readers": readers})
        # One loop re-ship can resend at most this much cached tail; the
        # unacked window is bounded by the pipeline's total buffering.
        self._cache_bound = len(self._edge_defs) * self._depth \
            + self._depth + 8

    def _create_participants(self):
        """One executor actor per FunctionNode; ClassMethodNodes adopt
        their user actor. All nodes of one actor share a single loop
        (separate loops would deadlock on the actor's concurrency
        slot)."""
        by_actor: Dict[Any, _Participant] = {}
        for k, node in enumerate(self._compute_nodes):
            if isinstance(node, FunctionNode):
                opts = {o: v for o, v in node._remote_fn._options.items()
                        if o in _EXECUTOR_OPTION_KEYS}
                fn = node._remote_fn._function
                handle = _executor_actor_class().options(
                    max_concurrency=1, **opts).remote(fn)
                self._executor_actors.append(handle)
                self._participants.append(
                    _Participant(handle, [k], True, fn, opts))
            else:
                handle = node._actor_method._handle
                p = by_actor.get(handle._actor_id)
                if p is None:
                    p = _Participant(handle, [], False)
                    by_actor[handle._actor_id] = p
                    self._participants.append(p)
                p.node_keys.append(k)
        self._part_of_key = {k: p for p in self._participants
                             for k in p.node_keys}

    def _pin(self, actor_ids: list, timeout_s: float) -> dict:
        """Pin (or re-pin, during recovery) `actor_ids`' worker leases at
        their hosting raylets; merges the fresh placements and prunes
        replaced participants'. dag_release() undoes the pins."""
        from ray_tpu._private import worker_api
        core = worker_api.get_core()
        placements = worker_api._call_on_core_loop(
            core, core.dag_pin_actors(self._dag_id, list(actor_ids),
                                      timeout_s=timeout_s),
            timeout_s + 15)
        self._placements.update(placements)
        current = {p.handle._actor_id for p in self._participants}
        self._placements = {a: pl for a, pl in self._placements.items()
                            if a in current}
        self._pinned_raylets = sorted(
            {pl["raylet"] for pl in self._placements.values()})
        if self._driver_node is None:
            self._driver_node = worker_api._call_on_core_loop(
                core, core.local_node_id(), 30)
        # Refresh the GCS drain index from the PRUNED footprint (a keyed
        # upsert): registering from inside dag_pin_actors would merge
        # replaced participants' old nodes in forever, and a later drain
        # of such a node would misreport this DAG as affected.
        try:
            worker_api._call_on_core_loop(
                core, core.dag_register(
                    self._dag_id,
                    [pl["node_id"] for pl in self._placements.values()]),
                15)
        except Exception:  # noqa: BLE001 — best-effort index
            pass
        return placements

    # -- channels ------------------------------------------------------
    def _place_of(self, entity) -> Any:
        if entity is None or entity == _DRIVER:
            return self._driver_node
        p = self._part_of_key[entity]
        pl = self._placements.get(p.handle._actor_id)
        return pl["node_id"] if pl else None

    def _edge_is_ring(self, edge: dict) -> bool:
        """A ring needs every endpoint on ONE node; any remote endpoint
        moves the whole edge to the KV/store fallback."""
        places = {self._place_of(edge["writer"])}
        places.update(self._place_of(r) for r in edge["readers"])
        return len(places) == 1 and None not in places

    def _make_edge_channel(self, edge: dict):
        if self._edge_is_ring(edge):
            ch = RingChannel(self._max_size, self._depth,
                             len(edge["readers"]))
        else:
            ch = StoreChannel(f"{self._dag_id}/{edge['key']}e{self._epoch}",
                              self._depth, len(edge["readers"]))
        self._channels.append(ch)
        self._edge_channels[edge["key"]] = ch
        return ch

    def _node_spec(self, k: int) -> tuple:
        """Per consumed value either a channel-read index or an inline
        constant; repeat reads collapse onto one reader."""
        node = self._compute_nodes[k]
        input_edge = self._edge_defs[0]
        in_readers: List[Any] = []
        reader_idx: Dict[Any, int] = {}

        def wire(value):
            if isinstance(value, InputNode):
                ekey, ridx = "input", input_edge["readers"].index(k)
            elif isinstance(value, DAGNode):
                up = self._key_of[id(value)]
                ekey, ridx = up, self._edge_defs[up + 1]["readers"].index(k)
            else:
                return ("const", -1, value)
            if ekey not in reader_idx:
                reader_idx[ekey] = len(in_readers)
                in_readers.append(self._edge_channels[ekey].reader(
                    ridx, patient=self._patient))
            return ("chan", reader_idx[ekey], None)

        arg_t = [wire(a) for a in node._bound_args]
        kw_t = []
        for key, v in node._bound_kwargs.items():
            kind, j, const = wire(v)
            kw_t.append((key, kind, j, const))
        if not in_readers:
            in_readers.append(self._edge_channels["input"].reader(
                input_edge["readers"].index(k), patient=self._patient))
        writer = self._edge_channels[k]
        if isinstance(writer, RingChannel):
            writer = writer.writer()
        return (in_readers, arg_t, kw_t, writer)

    def _ship_loops(self, resume_map: dict):
        """Ship ONE run loop per participant actor; resume directives
        ride along on recovery re-ships."""
        from ray_tpu.actor import ActorMethod
        for p in self._participants:
            specs = [self._node_spec(k) for k in p.node_keys]
            keys = list(p.node_keys)
            resume = {k: resume_map[k] for k in keys if k in resume_map}
            if p.is_fn:
                p.loop_ref = p.handle.run_loop.remote(
                    specs, keys, resume, self._cache_bound, self._dag_id,
                    self._tick_replay)
            else:
                loop_method = ActorMethod(p.handle, "__ray_tpu_dag_loop__")
                p.loop_ref = loop_method.remote(
                    [self._compute_nodes[k]._actor_method._name
                     for k in keys],
                    specs, keys, resume, self._cache_bound, self._dag_id,
                    self._tick_replay)
        self._loop_refs = [p.loop_ref for p in self._participants]

    def _refresh_driver_endpoints(self):
        self._input_writers = [self._edge_channels["input"]]
        out_unique: List[int] = []
        self._output_map = []
        for o in self._outputs:
            k = self._key_of[id(o)]
            if k not in out_unique:
                out_unique.append(k)
            self._output_map.append(out_unique.index(k))
        self._output_readers = [
            self._edge_channels[k].reader(
                self._edge_defs[k + 1]["readers"].index(_DRIVER),
                patient=self._patient)
            for k in out_unique]

    # ------------------------------------------------------------------
    # Failure watcher: push-based, parked on the loop refs
    # ------------------------------------------------------------------
    def _arm_watcher(self, core):
        import asyncio

        self._watch_epoch += 1
        epoch = self._watch_epoch
        refs = list(self._loop_refs)

        async def _watch():
            # Any settled loop ref before teardown = dead executor: the
            # loops only return once their channels close. get() digs
            # out the cause (ActorDiedError / WorkerCrashedError / app
            # failure in the loop plumbing).
            done, _ = await core.wait_async(refs, num_returns=1,
                                            timeout=None, fetch_local=False)
            try:
                await core.get_async([done[0]], 5)
                return RuntimeError("executor loop exited before teardown")
            except Exception as e:  # noqa: BLE001
                return e

        fut = asyncio.run_coroutine_threadsafe(_watch(), core.loop)

        def _on_done(f):
            # The epoch guard makes the watcher one-shot ACROSS recovery
            # passes: recovery bumps the epoch BEFORE its quiesce close,
            # so the loops it wakes ("executor loop exited") can never
            # re-trigger it — only a genuine post-recovery death fires
            # the freshly armed watcher.
            if f.cancelled() or self._torn_down \
                    or epoch != self._watch_epoch:
                return
            try:
                cause = f.result()
            except Exception as e:  # noqa: BLE001
                cause = e
            # Never block the core loop: recovery (or the typed fail)
            # runs on its own thread.
            threading.Thread(target=self._recover_or_fail,
                             args=(cause, epoch),
                             daemon=True, name="dag-recover").start()

        fut.add_done_callback(_on_done)
        self._watcher = fut

    def _fail(self, err: DagExecutionError):
        """Mark the DAG failed and wake EVERY blocked channel end: the
        in-flight execute raises typed instead of wedging, and so does
        every subsequent one."""
        if self._error is None:
            self._error = err
        self._state = "failed"
        self._recovered_evt.set()
        for ch in list(self._channels):
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — teardown race
                pass

    # ------------------------------------------------------------------
    # Recovery: recompile-in-place onto restarted participants
    # ------------------------------------------------------------------
    def _recover_or_fail(self, cause, epoch: int):
        """Watcher landing: a loop ref settled. Replayable DAGs recover
        in place; everything else keeps the typed fail-fast."""
        err = DagExecutionError("compiled DAG executor died mid-tick",
                                cause)
        if not self._tick_replay:
            self._fail(err)
            return
        with self._recover_lock:
            if self._torn_down or self._error is not None:
                return
            if epoch != self._watch_epoch:
                # A recovery/migration pass completed while this thread
                # waited for the lock: the failure that fired us was
                # re-probed (and handled) by that pass.
                return
            if self.recoveries >= self._max_recoveries:
                self._fail(err)
                return
            self._invalidate_watcher()
            self._state = "recovering"
            self._recovered_evt.clear()
            ok = self._run_recovery(cause, drain=None)
        if ok:
            self._replay_unacked()

    def _invalidate_watcher(self):
        """Retire the armed watcher before the quiesce close: the loops
        recovery wakes must not read as a fresh failure."""
        self._watch_epoch += 1
        if self._watcher is not None:
            self._watcher.cancel()

    def _run_recovery(self, cause, drain: Optional[dict]) -> bool:
        """Drive _recover under the held lock with bounded retries (a
        second death DURING recovery lands here as a failed attempt and
        is absorbed); finishes the state machine + metrics. Returns True
        once the DAG is RUNNING again."""
        t0 = time.time()
        attempts = 0
        while True:
            attempts += 1
            if self._torn_down:
                return False
            try:
                self._recover(cause, drain)
                break
            except _Unrecoverable as e:
                self._recovery_failed(e.error)
                return False
            except BaseException as e:  # noqa: BLE001
                if self._torn_down:
                    return False
                if attempts >= 3:
                    self._recovery_failed(e)
                    return False
                time.sleep(0.25)
        self.recoveries += 1
        self._state = "running"
        self._recovered_evt.set()
        now = time.time()
        try:
            m = _metric_handles()
            m["recoveries"].inc()
            m["recovery_s"].observe(now - t0)
        except Exception:  # noqa: BLE001 — metrics never block recovery
            pass
        self._export_span("dag:recover", t0, now)
        return True

    def _recovery_failed(self, cause: BaseException):
        """Recovery-failure path: surface typed, wake every blocked end,
        and release what the DAG still holds (re-pinned leases must not
        leak on a pipeline that will never tick again)."""
        err = cause if isinstance(cause, DagExecutionError) else \
            DagRecoveryError("compiled DAG recovery failed", cause)
        self._fail(err)
        self._release_pins()

    def _recover(self, cause, drain: Optional[dict] = None):
        """One recovery attempt (caller holds _recover_lock):

        1. quiesce — close every channel so all loops park and exit;
        2. classify — survivors returned "closed"; dead loops raised;
        3. restart — recreate dead FunctionNode executors, wait out the
           actor-restart/migration of user actors (a drain migrates ALL
           affected participants via the GCS, uncharged);
        4. re-pin only the restarted participants' leases (partial);
           release raylets the DAG no longer touches;
        5. channels — reopen surviving segments (contents + cursors
           kept); re-create only edges whose locality changed (re-home
           ring<->store);
        6. re-ship the run loops with resume directives; refresh driver
           endpoints; re-arm the watcher.

        The driver-side tick replay happens AFTER the lock drops (the
        caller drains outputs concurrently — replaying under the lock
        against a full ring would deadlock a single-threaded caller).
        """
        import ray_tpu
        from ray_tpu import exceptions as exc
        from ray_tpu._private import worker_api

        core = worker_api.get_core()
        deadline = time.time() + self._recovery_timeout_s
        drain_nodes = set(drain["node_ids"]) if drain else set()
        if drain:
            # Hand off the dying members' pins FIRST: the draining
            # raylet's drain_to_idle must never wait on this DAG.
            stale = [a for a in self._pinned_raylets
                     if a in set(drain.get("addrs") or ())]
            if stale:
                try:
                    worker_api._call_on_core_loop(
                        core, core.dag_release(self._dag_id, stale), 30)
                except Exception:  # noqa: BLE001 — raylet may be gone
                    pass

        # 1 + 2: quiesce and classify.
        for ch in list(self._channels):
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        dead: List[_Participant] = []
        for p in self._participants:
            try:
                ray_tpu.get(p.loop_ref,
                            timeout=max(1.0, deadline - time.time()))
            except exc.GetTimeoutError:
                raise DagExecutionError(
                    "surviving executor loop did not quiesce within the "
                    "recovery timeout")
            except Exception:  # noqa: BLE001 — death cause re-derived below
                dead.append(p)

        # 3: restart the dead / drained participants.
        to_restart: List[_Participant] = list(dead)
        for p in self._participants:
            if p in dead:
                continue
            pl = self._placements.get(p.handle._actor_id)
            if drain_nodes and pl and pl.get("node_id") in drain_nodes:
                to_restart.append(p)
        for p in to_restart:
            info = self._actor_state(core, p)
            state = getattr(info, "state", "DEAD") if info else "DEAD"
            if p.is_fn and (info is None or state == "DEAD"):
                # DAG-owned executor with no restart budget: recreate it
                # ourselves (same fn, same options).
                try:
                    ray_tpu.kill(p.handle)
                except Exception:  # noqa: BLE001 — already gone
                    pass
                p.handle = _executor_actor_class().options(
                    max_concurrency=1, **dict(p.fn_opts)).remote(p.fn)
                self._executor_actors.append(p.handle)
            else:
                # User actor (or a drain-migrated executor): ride its own
                # restart — max_restarts, or the uncharged
                # preempted_restarts migration a drain already kicked.
                self._wait_participant_alive(core, p, drain_nodes, deadline)
        self._part_of_key = {k: p for p in self._participants
                             for k in p.node_keys}

        # 4: partial re-pin + stale-raylet release.
        old_raylets = set(self._pinned_raylets)
        restarted_ids = [p.handle._actor_id for p in to_restart]
        for p in to_restart:
            # Drop the replaced incarnation's placement so _pin prunes
            # cleanly (recreated executors have a NEW actor id).
            self._placements.pop(p.handle._actor_id, None)
        if restarted_ids:
            self._pin(restarted_ids, max(5.0, deadline - time.time()))
        stale = sorted(old_raylets - set(self._pinned_raylets))
        if stale:
            try:
                worker_api._call_on_core_loop(
                    core, core.dag_release(self._dag_id, stale), 30)
            except Exception:  # noqa: BLE001
                pass

        # 5: per-edge channel keep/reopen vs re-create/re-home.
        self._epoch += 1
        restarted_keys = {k for p in to_restart for k in p.node_keys}
        for edge in self._edge_defs:
            ch = self._edge_channels[edge["key"]]
            want_ring = self._edge_is_ring(edge)
            if want_ring == isinstance(ch, RingChannel):
                ch.reopen()
            else:
                try:
                    ch.destroy()
                except Exception:  # noqa: BLE001
                    pass
                if ch in self._channels:
                    self._channels.remove(ch)
                self._make_edge_channel(edge)

        # 6: resume directives + re-ship. EVERY survivor re-emits its
        # cached tail from the replay floor: a quiesce can interrupt any
        # node between caching a result and delivering it (the write
        # raised ChannelClosedError), so every edge is potentially one
        # tick short — and duplicates are filtered by sequence at every
        # reader, so the blanket resend is safe where a lossy-edges-only
        # resend provably is not.
        replay_floor = self._collected
        resume: Dict[int, dict] = {}
        for k in range(len(self._compute_nodes)):
            d = {"start": replay_floor, "careful": self._cache_bound}
            if k not in restarted_keys:
                d["resend_from"] = replay_floor
            resume[k] = d
        if self._torn_down:
            raise _Unrecoverable(RuntimeError("compiled DAG was torn down"))
        self._ship_loops(resume)
        self._refresh_driver_endpoints()
        self._arm_watcher(core)

    def _actor_state(self, core, p: _Participant):
        from ray_tpu._private import worker_api
        try:
            return worker_api._call_on_core_loop(
                core, core.gcs.request("get_actor_info",
                                       {"actor_id": p.handle._actor_id}), 10)
        except Exception:  # noqa: BLE001 — GCS hiccup: treat as unknown
            return None

    def _wait_participant_alive(self, core, p: _Participant, avoid_nodes,
                                deadline: float):
        """Wait until the participant is ALIVE off `avoid_nodes` (its
        restart is the GCS's job — max_restarts for kills, uncharged
        migration for drains). DEAD-for-good is unrecoverable."""
        while True:
            info = self._actor_state(core, p)
            state = getattr(info, "state", None)
            if info is not None and state == "ALIVE" \
                    and info.node_id is not None \
                    and info.node_id not in avoid_nodes:
                return info
            if info is not None and state == "DEAD":
                raise _Unrecoverable(DagRecoveryError(
                    "participant actor died for good (max_restarts "
                    "exhausted?) — cannot recompile in place",
                    DagExecutionError("compiled DAG executor died",
                                      None)))
            if time.time() > deadline:
                raise DagExecutionError(
                    "timed out waiting for a participant restart during "
                    "DAG recovery")
            time.sleep(0.05)

    def _replay_unacked(self) -> int:
        """Re-drive every unacknowledged tick from the driver-side replay
        buffer. Runs OUTSIDE the recovery lock: writes can block on a
        full input ring and only the caller's collect drains the far
        end. Duplicate deliveries are dropped by sequence everywhere, so
        replaying a tick that survived inside a kept ring is harmless."""
        epoch = self._watch_epoch
        n = 0
        for seq in sorted(self._replay):
            if seq < self._collected:
                continue
            while True:
                if self._torn_down or self._error is not None \
                        or epoch != self._watch_epoch:
                    return n
                if seq not in self._replay:
                    break  # collected while we were replaying
                value = self._replay[seq]
                try:
                    with self._submit_lock:
                        for w in self._input_writers:
                            w.write((seq, value), timeout=0.25)
                    n += 1
                    break
                except TimeoutError:
                    continue  # ring full: release the lock, retry
                except ChannelClosedError:
                    return n  # a newer recovery pass took over
        if n:
            self.replayed_ticks += n
            try:
                _metric_handles()["replayed"].inc(n)
            except Exception:  # noqa: BLE001
                pass
        return n

    def _release_pins(self):
        """Release every lease this DAG still pins (idempotent)."""
        try:
            from ray_tpu._private import worker_api
            core = worker_api.peek_core()
            if core is not None and self._pinned_raylets:
                worker_api._call_on_core_loop(
                    core, core.dag_release(self._dag_id,
                                           list(self._pinned_raylets),
                                           unregister=True), 30)
        except Exception:  # noqa: BLE001 — cluster already down
            pass

    # ------------------------------------------------------------------
    # Drain-aware proactive migration
    # ------------------------------------------------------------------
    def _register_drain_listener(self):
        try:
            from ray_tpu._private import worker_api
            self._drain_seen = len(worker_api.drain_events())
            if worker_api.add_drain_event_listener(self._on_drain_notice):
                self._drain_cb = self._on_drain_notice
        except Exception:  # noqa: BLE001 — driver without a core
            pass

    def _unregister_drain_listener(self):
        if self._drain_cb is not None:
            try:
                from ray_tpu._private import worker_api
                worker_api.remove_drain_event_listener(self._drain_cb)
            except Exception:  # noqa: BLE001
                pass
            self._drain_cb = None

    def _on_drain_notice(self):
        """Core-loop callback on every drain/preemption notice: cheap
        overlap check, then migration on its own thread."""
        try:
            if self._torn_down or self._error is not None \
                    or self._migration_inflight:
                return
            from ray_tpu._private import worker_api
            events = worker_api.drain_events()
            fresh, self._drain_seen = events[self._drain_seen:], len(events)
            if not fresh:
                return
            my_nodes = {pl["node_id"]
                        for pl in self._placements.values()}
            hit_nodes, hit_addrs, ddl = set(), set(), 0.0
            for ev in fresh:
                ids = list(ev.get("node_ids") or [])
                if not ids and ev.get("node_id") is not None:
                    ids = [ev["node_id"]]
                ads = list(ev.get("addresses") or [])
                if not ads and ev.get("address"):
                    ads = [ev["address"]]
                dag_ids = ev.get("dag_ids")
                if (dag_ids and self._dag_id in dag_ids) \
                        or any(i in my_nodes for i in ids):
                    hit_nodes.update(ids)
                    hit_addrs.update(ads)
                    ddl = max(ddl, float(ev.get("deadline", 0.0)))
            if hit_nodes & my_nodes:
                self._migration_inflight = True
                threading.Thread(
                    target=self._drain_migrate,
                    args=(hit_nodes, hit_addrs, ddl),
                    daemon=True, name="dag-migrate").start()
        except Exception:  # noqa: BLE001 — listeners must not break pubsub
            pass

    def _drain_migrate(self, node_ids: set, addrs: set,
                       drain_deadline: float):
        """Proactive migration off draining nodes: same recompile-in-place
        machinery, entered BEFORE the kill — a drain with notice costs
        zero failed ticks. Replayable DAGs cut over immediately (the
        replay buffer completes in-flight ticks); non-replayable ones
        migrate only from a quiesced pipeline (otherwise they keep
        today's typed fail-fast when the deadline kill lands)."""
        ok = False
        try:
            with self._recover_lock:
                if self._torn_down or self._error is not None \
                        or self._state != "running":
                    return
                affected = [
                    p for p in self._participants
                    if (self._placements.get(p.handle._actor_id) or {})
                    .get("node_id") in node_ids]
                if not affected:
                    return
                self._state = "recovering"
                self._recovered_evt.clear()
                if not self._tick_replay:
                    budget = (drain_deadline - time.time() - 1.0) \
                        if drain_deadline else 5.0
                    qd = time.monotonic() + max(0.5, budget)
                    while self._inflight > 0 and time.monotonic() < qd:
                        time.sleep(0.01)
                    if self._inflight > 0:
                        # Can't drain the pipeline in time: leave it
                        # running; the deadline kill surfaces as the
                        # typed failure it always was.
                        self._state = "running"
                        self._recovered_evt.set()
                        return
                self._invalidate_watcher()
                ok = self._run_recovery(
                    NodeDrainedCause(list(node_ids)),
                    drain={"node_ids": set(node_ids), "addrs": set(addrs)})
            if ok and self._tick_replay:
                self._replay_unacked()
        finally:
            self._migration_inflight = False
            # Notices that landed WHILE this migration ran were left
            # unconsumed by the listener (it early-returns on the
            # inflight flag without advancing _drain_seen): reprocess
            # them now, or a second node's drain would never migrate
            # proactively.
            try:
                self._on_drain_notice()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def _await_running(self, deadline: Optional[float] = None):
        """Block while a recovery pass owns the pipeline; re-raise the
        typed error if it failed instead."""
        while not self._recovered_evt.wait(timeout=0.25):
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    "compiled DAG still recovering past the deadline")
        if self._error is not None:
            raise self._error
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")

    def execute(self, *args, timeout: Optional[float] = None) -> Any:
        """One pipeline tick, synchronously: channel write + read."""
        return self.execute_async(*args).result(timeout)

    def execute_async(self, *args) -> "DagRef":
        """Submit a tick without waiting for its output: overlapping
        executions are bounded by the channel depth (the input write
        blocks once `depth` ticks are in flight — backpressure, not an
        error). A single-threaded caller must therefore collect results
        at least every `channel_depth` submissions (see
        StagePipeline.run for the windowed pattern); submitting
        unboundedly ahead would block this write with nobody draining
        the output rings. During a recovery pass submission blocks until
        the pipeline is RUNNING again (or raises its typed error)."""
        value = args[0] if len(args) == 1 else args
        deadline = time.monotonic() + self._recovery_timeout_s + 30.0
        closed_retries = 0
        while True:
            self._await_running(deadline)
            with self._submit_lock:
                if not self._recovered_evt.is_set():
                    continue  # a recovery started while we waited
                seq = self._next_seq
                try:
                    for w in self._input_writers:
                        w.write((seq, value))
                except ChannelClosedError:
                    if self._error is not None:
                        raise self._error
                    if self._torn_down:
                        raise RuntimeError("compiled DAG was torn down")
                    closed_retries += 1
                    if closed_retries > 400:
                        raise DagExecutionError(
                            "compiled DAG channel closed unexpectedly")
                    time.sleep(0.02)
                    continue  # closed for recovery: wait it out
                self._next_seq = seq + 1
                if self._tick_replay:
                    self._replay[seq] = value
                self._submit_ts[seq] = time.time()
                self._inflight += 1
                self.max_inflight = max(self.max_inflight, self._inflight)
                try:
                    _metric_handles()["inflight"].set(float(self._inflight))
                except Exception:  # noqa: BLE001 — metrics never block ticks
                    pass
            return DagRef(self, seq)

    def _read_outputs(self, want: int, deadline: Optional[float]) -> list:
        """Drain EVERY output for tick `want` (an unread channel would
        hand this tick's value to the next collect); the same node bound
        twice in a MultiOutputNode shares one reader — read it once.
        Messages are (seq, value): duplicates below `want` (post-recovery
        resends) are dropped, ahead-of-target values are stashed — which
        also makes a result() timeout resumable (the drained readers'
        cursors advanced persistently). copy=True detaches results from
        the ring slots the writer will recycle `depth` ticks from now —
        callers may hold results indefinitely."""
        for idx, r in enumerate(self._output_readers):
            stash = self._out_stash.setdefault(idx, {})
            for stale in [s for s in stash if s < want]:
                del stash[s]
            while want not in stash:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                seq, val = r.read(timeout=remaining, copy=True)
                if seq >= want:
                    stash[seq] = val
        outs = [self._out_stash[ridx][want] for ridx in self._output_map]
        for idx in range(len(self._output_readers)):
            self._out_stash.get(idx, {}).pop(want, None)
        return outs

    def _collect(self, seq: int, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._collect_lock:
            if seq < self._collected and seq not in self._results:
                raise ValueError(
                    f"DagRef for tick {seq} was already consumed — "
                    f"result() is one-shot")
            closed_retries = 0
            while seq not in self._results:
                if self._error is not None:
                    raise self._error
                want = self._collected
                try:
                    outs = self._read_outputs(want, deadline)
                except ChannelClosedError:
                    if self._error is not None:
                        raise self._error
                    if self._torn_down:
                        raise RuntimeError("compiled DAG was torn down")
                    closed_retries += 1
                    if closed_retries > 400:
                        raise DagExecutionError(
                            "compiled DAG channel closed unexpectedly")
                    # Recovery in flight: wait for it, then resume the
                    # drain against the refreshed readers.
                    time.sleep(0.02)
                    self._await_running(deadline)
                    continue
                closed_retries = 0
                done_seq = self._collected
                self._collected += 1
                self._results[done_seq] = outs
                self._replay.pop(done_seq, None)
                self._inflight -= 1
                self.ticks += 1
                t0 = self._submit_ts.pop(done_seq, None)
                now = time.time()
                try:
                    m = _metric_handles()
                    if t0 is not None:
                        m["tick"].observe(now - t0)
                    m["inflight"].set(float(self._inflight))
                except Exception:  # noqa: BLE001
                    pass
                if t0 is not None:
                    self._export_span("dag:tick", t0, now,
                                      only_if_traced=True)
            outs = self._results.pop(seq)
        err = next((o for o in outs if isinstance(o, _DagError)), None)
        if err is not None:
            raise err.error
        return outs if len(outs) > 1 else outs[0]

    def stats(self) -> dict:
        return {"dag_id": self._dag_id, "ticks": self.ticks,
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "channels": len(self._channels),
                "pinned_raylets": list(self._pinned_raylets),
                "state": self._state,
                "tick_replay": self._tick_replay,
                "recoveries": self.recoveries,
                "replayed_ticks": self.replayed_ticks}

    # ------------------------------------------------------------------
    # Teardown
    # ------------------------------------------------------------------
    def teardown(self):
        """Release every compile-time acquisition: close channels (run
        loops exit), await the loops, release pinned leases, kill
        executor actors, unlink every shm segment / KV record."""
        if self._torn_down:
            return
        self._torn_down = True
        self._state = "torn_down"
        self._recovered_evt.set()
        if self._watcher is not None:
            self._watcher.cancel()
        self._watch_epoch += 1
        self._unregister_drain_listener()
        import ray_tpu
        # Close BEFORE waiting: a loop blocked mid-read anywhere in the
        # pipeline only exits once its channels wake it.
        for ch in list(self._channels):
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:  # noqa: BLE001 — dead executor: lease died
                pass
        self._release_pins()
        for a in self._executor_actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        for ch in list(self._channels):
            try:
                ch.destroy()
            except Exception:  # noqa: BLE001
                pass
        try:
            _metric_handles()["inflight"].set(0.0)
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _export_span(self, name: str, start: float, end: float,
                     only_if_traced: bool = False):
        try:
            from ray_tpu.util import tracing
            if only_if_traced and not tracing.is_enabled():
                return
            from ray_tpu._private import flightrec
            tracing.export_span(flightrec.span_event(
                name, f"dag:{self._dag_id}", start, end))
        except Exception:  # noqa: BLE001 — observability never blocks
            pass


class NodeDrainedCause(Exception):
    """Cause marker for drain-triggered (proactive) recoveries."""

    def __init__(self, node_ids):
        names = []
        for n in node_ids:
            try:
                names.append(n.hex()[:12])
            except AttributeError:
                names.append(str(n))
        super().__init__(f"nodes draining: {names}")


class DagRef:
    """Handle to one submitted tick; `result()` blocks for its outputs.
    Outputs complete strictly in submission order (the pipeline is
    FIFO), so collecting a later ref first also drains earlier ones."""

    __slots__ = ("_dag", "_seq")

    def __init__(self, dag: CompiledDAG, seq: int):
        self._dag = dag
        self._seq = seq

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._dag._collect(self._seq, timeout)

    def done(self) -> bool:
        return self._seq in self._dag._results \
            or self._seq < self._dag._collected


_executor_cls = None


def _executor_actor_class():
    """Defers the @remote wrapping until first use (import order)."""
    global _executor_cls
    if _executor_cls is None:
        import ray_tpu

        @ray_tpu.remote
        class _DAGExecutor:
            """Hosts FunctionNode loops (reference: CompiledTask worker)."""

            def __init__(self, fn):
                self._fn = fn
                self._dag_state = {}

            def run_loop(self, node_specs, node_keys=None, resume=None,
                         cache_bound=64, dag_id="", detach=False):
                return _run_compiled_loop(
                    [self._fn] * len(node_specs), node_specs, node_keys,
                    self._dag_state.setdefault(dag_id, {}), resume,
                    cache_bound, detach)

        _executor_cls = _DAGExecutor
    return _executor_cls
