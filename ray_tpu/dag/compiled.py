"""Compiled DAGs: the graph lowered onto persistent executors + mutable
shared-memory channels.

Reference: python/ray/dag/compiled_dag_node.py:141 (CompiledDAG /
CompiledTask). Instead of one task/actor RPC round trip per node per
call (~1 ms each), compilation starts ONE long-running loop per executor
that blocks on its input channels, runs its bound functions/methods, and
writes output channels — execute() then costs one channel write + one
read. All nodes bound to the same actor run inside a single loop (the
reference runs an actor's compiled tasks on one executable loop too), so
an actor is pinned by exactly one long-running task until teardown().
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                                  InputNode, MultiOutputNode)
from ray_tpu.experimental.channel import Channel, ChannelClosedError


class _DagError:
    """Error marker shipped through a channel; re-raised at the consumer."""

    def __init__(self, error: Exception):
        self.error = error


def _run_compiled_loop(fns: List, node_specs: List[tuple]):
    """One executor loop driving one or more compiled nodes.

    node_specs[i] = (in_channels, arg_template, kw_template, out_channel)
    for fns[i], in topological order — intra-executor edges resolve
    because the producer's channel was written earlier in the same pass.
    pickle memoization can alias two in_channels entries to one attached
    object; each distinct channel is read once per pass.
    """
    while True:
        read_cache: Dict[int, Any] = {}
        closed = False
        for fn, (in_channels, arg_t, kw_t, out_channel) in zip(fns,
                                                               node_specs):
            if closed:
                out_channel.close()
                continue
            values = []
            try:
                for ch in in_channels:
                    if id(ch) not in read_cache:
                        read_cache[id(ch)] = ch.read()
                    values.append(read_cache[id(ch)])
            except ChannelClosedError:
                out_channel.close()
                closed = True
                continue
            except Exception as e:  # noqa: BLE001 — a read error must
                # surface to the caller as a typed result, never kill the
                # loop silently: a dead loop leaves every later execute()
                # spinning on an output channel nobody will write.
                out_channel.write(_DagError(e))
                read_cache[id(out_channel)] = _DagError(e)
                continue
            err = next((v for v in values if isinstance(v, _DagError)),
                       None)
            if err is not None:
                out_channel.write(err)
                read_cache[id(out_channel)] = err
                continue
            args = [values[i] if kind == "chan" else const
                    for kind, i, const in arg_t]
            kwargs = {key: (values[i] if kind == "chan" else const)
                      for key, kind, i, const in kw_t}
            try:
                result = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                result = _DagError(e)
            out_channel.write(result)
            # Intra-executor consumers read the fresh value from cache
            # (their reader cursor may lag the channel version).
            read_cache[id(out_channel)] = result
        if closed:
            return "closed"


def _dag_loop_method(self, method_names: List[str], node_specs: List[tuple]):
    """Injected onto every actor instance (core_worker instantiation) so a
    compiled DAG can pin a loop to a user actor without the class opting
    in (reference: aDAG's internal actor executables)."""
    return _run_compiled_loop([getattr(self, m) for m in method_names],
                              node_specs)


_EXECUTOR_OPTION_KEYS = ("num_cpus", "num_tpus", "num_gpus", "resources",
                         "scheduling_strategy", "runtime_env")


class CompiledDAG:
    def __init__(self, root: DAGNode, max_message_size: int = 1 << 20):
        self._root = root
        self._max_size = max_message_size
        self._nodes = root._topo()
        self._input_channel = Channel(max_message_size)
        self._channels: Dict[int, Channel] = {}
        self._loop_refs: List[Any] = []
        self._executor_actors: List[Any] = []
        self._torn_down = False

        multi = isinstance(root, MultiOutputNode)
        compute_nodes: List[DAGNode] = []
        for node in self._nodes:
            if isinstance(node, InputNode):
                self._channels[id(node)] = self._input_channel
            elif isinstance(node, (FunctionNode, ClassMethodNode)):
                self._channels[id(node)] = Channel(max_message_size)
                compute_nodes.append(node)
            elif isinstance(node, MultiOutputNode):
                if node is not root:
                    raise ValueError("MultiOutputNode must be the DAG root")
            else:
                raise TypeError(f"cannot compile node {node!r}")
        if multi:
            self._output_channels = [self._channels[id(o)]
                                     for o in root._bound_args]
        else:
            self._output_channels = [self._channels[id(root)]]

        # Group nodes into executors: one per FunctionNode, one per ACTOR
        # (all of an actor's nodes share a single loop; separate loops
        # would deadlock on the actor's concurrency slot).
        actor_groups: Dict[Any, List[ClassMethodNode]] = {}
        for node in compute_nodes:
            spec = self._node_spec(node)
            if isinstance(node, FunctionNode):
                opts = {k: v for k, v in node._remote_fn._options.items()
                        if k in _EXECUTOR_OPTION_KEYS}
                executor = _executor_actor_class().options(
                    max_concurrency=1, **opts).remote(
                        node._remote_fn._function)
                self._executor_actors.append(executor)
                self._loop_refs.append(
                    executor.run_loop.remote([spec]))
            else:
                handle = node._actor_method._handle
                actor_groups.setdefault(handle._actor_id, (handle, []))
                actor_groups[handle._actor_id][1].append(node)
        for handle, nodes in actor_groups.values():
            from ray_tpu.actor import ActorMethod
            loop_method = ActorMethod(handle, "__ray_tpu_dag_loop__")
            self._loop_refs.append(loop_method.remote(
                [n._actor_method._name for n in nodes],
                [self._node_spec(n) for n in nodes]))

    def _node_spec(self, node: DAGNode) -> tuple:
        in_channels: List[Channel] = []
        arg_t: List[tuple] = []
        kw_t: List[tuple] = []

        def wire(value):
            if isinstance(value, DAGNode):
                in_channels.append(self._channels[id(value)])
                return ("chan", len(in_channels) - 1, None)
            return ("const", -1, value)

        for a in node._bound_args:
            arg_t.append(wire(a))
        for k, v in node._bound_kwargs.items():
            kind, i, const = wire(v)
            kw_t.append((k, kind, i, const))
        if not in_channels:
            # Const-only node: the input channel is its trigger, else the
            # loop would spin hot and never observe teardown.
            in_channels.append(self._input_channel)
        return (in_channels, arg_t, kw_t, self._channels[id(node)])

    def execute(self, *args) -> Any:
        """One synchronous pass through the pipeline: channel write + read."""
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        value = args[0] if len(args) == 1 else args
        self._input_channel.write(value)
        # Drain EVERY output before raising: an unread channel would hand
        # this pass's value to the next execute() (stale-read hazard).
        outs = [self._read_output(ch) for ch in self._output_channels]
        err = next((o for o in outs if isinstance(o, _DagError)), None)
        if err is not None:
            raise err.error
        return outs if len(outs) > 1 else outs[0]

    def _read_output(self, ch) -> Any:
        """Channel read with a liveness backstop: an executor whose loop
        died (worker crash, failed actor creation) will never write this
        channel — without the check, execute() spins on the seqlock
        until some outer timeout kills the caller."""
        while True:
            try:
                return ch.read(timeout=1.0)
            except TimeoutError:
                self._raise_if_executor_dead()

    def _raise_if_executor_dead(self):
        import ray_tpu
        # timeout must be > 0: wait(timeout=0) returns before the ready
        # probes get a single loop tick, i.e. it never reports anything
        # done.
        done, _pending = ray_tpu.wait(
            list(self._loop_refs), num_returns=len(self._loop_refs),
            timeout=0.2)
        for ref in done:
            # run_loop only returns at teardown: any settled ref here is
            # a dead executor. get() re-raises its error (ActorDiedError,
            # creation failure); a clean exit still means no writer.
            ray_tpu.get(ref, timeout=5)
            raise RuntimeError(
                "compiled DAG executor loop exited before teardown")

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu
        self._input_channel.close()
        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception:
                pass
        for a in self._executor_actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for ch in self._channels.values():
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


_executor_cls = None


def _executor_actor_class():
    """Defers the @remote wrapping until first use (import order)."""
    global _executor_cls
    if _executor_cls is None:
        import ray_tpu

        @ray_tpu.remote
        class _DAGExecutor:
            """Hosts FunctionNode loops (reference: CompiledTask worker)."""

            def __init__(self, fn):
                self._fn = fn

            def run_loop(self, node_specs):
                return _run_compiled_loop([self._fn] * len(node_specs),
                                          node_specs)

        _executor_cls = _DAGExecutor
    return _executor_cls
